"""TLS tests over real sockets (reference integration_test.rs:576-794 cert
rotation with rcgen-generated certs, and the TLS/mTLS matrix at 1017-1144):
serving with TLS, mTLS accept/reject, hot rotation semantics (both files
changed → swap; one file changed → keep old identity)."""

from __future__ import annotations

import datetime
import socket
import ssl

import pytest
import requests

pytest.importorskip("cryptography")

from policy_server_tpu import certs as certs_mod
from policy_server_tpu.config.config import TlsConfig

from cryptography import x509
from cryptography.hazmat.primitives import hashes, serialization
from cryptography.hazmat.primitives.asymmetric import ec
from cryptography.x509.oid import NameOID

from test_server import ServerHandle, make_config, pod_review_body


def _name(cn: str) -> x509.Name:
    return x509.Name([x509.NameAttribute(NameOID.COMMON_NAME, cn)])


def make_cert(cn: str, issuer_key=None, issuer_name=None, is_ca=False):
    """→ (key, cert). Self-signed when no issuer is given."""
    key = ec.generate_private_key(ec.SECP256R1())
    subject = _name(cn)
    issuer = issuer_name if issuer_name is not None else subject
    signing_key = issuer_key if issuer_key is not None else key
    now = datetime.datetime.now(datetime.timezone.utc)
    builder = (
        x509.CertificateBuilder()
        .subject_name(subject)
        .issuer_name(issuer)
        .public_key(key.public_key())
        .serial_number(x509.random_serial_number())
        .not_valid_before(now - datetime.timedelta(minutes=5))
        .not_valid_after(now + datetime.timedelta(days=1))
        .add_extension(
            x509.BasicConstraints(ca=is_ca, path_length=None), critical=True
        )
        .add_extension(
            x509.SubjectAlternativeName(
                [x509.DNSName("localhost"), x509.IPAddress(__import__("ipaddress").ip_address("127.0.0.1"))]
            ),
            critical=False,
        )
    )
    cert = builder.sign(signing_key, hashes.SHA256())
    return key, cert


def write_pem(tmp_path, name, key, cert):
    cert_path = tmp_path / f"{name}.crt"
    key_path = tmp_path / f"{name}.key"
    cert_path.write_bytes(cert.public_bytes(serialization.Encoding.PEM))
    key_path.write_bytes(
        key.private_bytes(
            serialization.Encoding.PEM,
            serialization.PrivateFormat.PKCS8,
            serialization.NoEncryption(),
        )
    )
    return cert_path, key_path


@pytest.fixture()
def tls_server(tmp_path):
    ca_key, ca_cert = make_cert("test-ca", is_ca=True)
    srv_key, srv_cert = make_cert(
        "localhost", issuer_key=ca_key, issuer_name=ca_cert.subject
    )
    cert_path, key_path = write_pem(tmp_path, "server", srv_key, srv_cert)
    ca_path = tmp_path / "ca.crt"
    ca_path.write_bytes(ca_cert.public_bytes(serialization.Encoding.PEM))
    config = make_config(
        tls_config=TlsConfig(cert_file=str(cert_path), key_file=str(key_path))
    )
    handle = ServerHandle(config)
    yield handle, tmp_path, (ca_key, ca_cert), (cert_path, key_path), ca_path
    handle.stop()
    rel = getattr(handle.server.tls_context, "_reloadable", None)
    if rel:
        rel.stop()


def https_url(handle: ServerHandle, path: str) -> str:
    return f"https://127.0.0.1:{handle.server.api_port}{path}"


def serial_of_served_cert(port: int) -> int:
    raw = ssl.get_server_certificate(("127.0.0.1", port))
    cert = x509.load_pem_x509_certificate(raw.encode())
    return cert.serial_number


def test_tls_serving_and_verification(tls_server):
    handle, tmp_path, _, _, ca_path = tls_server
    r = requests.post(
        https_url(handle, "/validate/pod-privileged"),
        json=pod_review_body(False),
        verify=str(ca_path),
        timeout=30,
    )
    assert r.status_code == 200 and r.json()["response"]["allowed"] is True
    # wrong CA → TLS failure
    with pytest.raises(requests.exceptions.SSLError):
        requests.post(
            https_url(handle, "/validate/pod-privileged"),
            json=pod_review_body(False),
            verify=True,
            timeout=30,
        )


def test_certificate_hot_rotation_both_files(tls_server):
    """Both cert+key replaced → the served identity swaps within the watch
    interval (integration_test.rs:576-722)."""
    import time

    handle, tmp_path, (ca_key, ca_cert), (cert_path, key_path), ca_path = tls_server
    before = serial_of_served_cert(handle.server.api_port)
    new_key, new_cert = make_cert(
        "localhost", issuer_key=ca_key, issuer_name=ca_cert.subject
    )
    cert_path.write_bytes(new_cert.public_bytes(serialization.Encoding.PEM))
    key_path.write_bytes(
        new_key.private_bytes(
            serialization.Encoding.PEM,
            serialization.PrivateFormat.PKCS8,
            serialization.NoEncryption(),
        )
    )
    deadline = time.time() + 10
    while time.time() < deadline:
        if serial_of_served_cert(handle.server.api_port) == new_cert.serial_number:
            break
        time.sleep(0.25)
    after = serial_of_served_cert(handle.server.api_port)
    assert after == new_cert.serial_number and after != before
    # still serves requests with the new identity
    r = requests.post(
        https_url(handle, "/validate/pod-privileged"),
        json=pod_review_body(False),
        verify=str(ca_path),
        timeout=30,
    )
    assert r.status_code == 200


def test_certificate_rotation_single_file_ignored(tls_server):
    """Only the cert replaced (key unchanged) → identity must NOT swap
    (integration_test.rs:724-742)."""
    import time

    handle, tmp_path, (ca_key, ca_cert), (cert_path, key_path), _ = tls_server
    before = serial_of_served_cert(handle.server.api_port)
    new_key, new_cert = make_cert(
        "localhost", issuer_key=ca_key, issuer_name=ca_cert.subject
    )
    cert_path.write_bytes(new_cert.public_bytes(serialization.Encoding.PEM))
    time.sleep(2.5)  # > watch interval
    assert serial_of_served_cert(handle.server.api_port) == before


def test_mtls_requires_client_cert(tmp_path):
    ca_key, ca_cert = make_cert("test-ca", is_ca=True)
    client_ca_key, client_ca_cert = make_cert("client-ca", is_ca=True)
    srv_key, srv_cert = make_cert(
        "localhost", issuer_key=ca_key, issuer_name=ca_cert.subject
    )
    cert_path, key_path = write_pem(tmp_path, "server", srv_key, srv_cert)
    ca_path = tmp_path / "ca.crt"
    ca_path.write_bytes(ca_cert.public_bytes(serialization.Encoding.PEM))
    client_ca_path = tmp_path / "client-ca.crt"
    client_ca_path.write_bytes(
        client_ca_cert.public_bytes(serialization.Encoding.PEM)
    )
    client_key, client_cert = make_cert(
        "client", issuer_key=client_ca_key, issuer_name=client_ca_cert.subject
    )
    client_cert_path, client_key_path = write_pem(
        tmp_path, "client", client_key, client_cert
    )
    config = make_config(
        tls_config=TlsConfig(
            cert_file=str(cert_path),
            key_file=str(key_path),
            client_ca_file=(str(client_ca_path),),
        )
    )
    handle = ServerHandle(config)
    try:
        # with client cert: accepted
        r = requests.post(
            https_url(handle, "/validate/pod-privileged"),
            json=pod_review_body(False),
            verify=str(ca_path),
            cert=(str(client_cert_path), str(client_key_path)),
            timeout=30,
        )
        assert r.status_code == 200
        # without client cert: TLS-level rejection. Depending on whether
        # the server's alert or the socket reset wins the race, requests
        # surfaces SSLError or its ConnectionError parent — match the
        # parent, which covers both.
        with pytest.raises(requests.exceptions.ConnectionError):
            requests.post(
                https_url(handle, "/validate/pod-privileged"),
                json=pod_review_body(False),
                verify=str(ca_path),
                timeout=30,
            )
    finally:
        handle.stop()


def test_multi_cert_file_rejected(tmp_path):
    key, cert = make_cert("localhost")
    pem = cert.public_bytes(serialization.Encoding.PEM)
    cert_path = tmp_path / "two.crt"
    cert_path.write_bytes(pem + pem)
    key_path = tmp_path / "one.key"
    key_path.write_bytes(
        key.private_bytes(
            serialization.Encoding.PEM,
            serialization.PrivateFormat.PKCS8,
            serialization.NoEncryption(),
        )
    )
    with pytest.raises(certs_mod.TlsConfigError, match="one certificate"):
        certs_mod.build_tls_server_config(
            TlsConfig(cert_file=str(cert_path), key_file=str(key_path))
        )
