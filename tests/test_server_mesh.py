"""--mesh must change the actual serving path (VERDICT r1 #1b).

The reference's scale-out is replicas behind a Service (README.md:21-26);
here the equivalent is the device mesh, so the server bootstrap must build
it and serve through it — not parse the flag and drop it. Runs on the
8-virtual-CPU-device platform from conftest.py (the v5e-8 stand-in)."""

from __future__ import annotations

import requests

from policy_server_tpu.config.config import MeshSpec
from policy_server_tpu.parallel import PolicyShardedEvaluator
from policy_server_tpu.telemetry import metrics as metrics_mod

from test_server import ServerHandle, make_config, pod_review_body


def test_data_mesh_attached_and_serving():
    """--mesh data:8 → one fused program, batch-sharded over 8 devices."""
    metrics_mod.reset_metrics_for_tests()
    handle = ServerHandle(make_config(mesh=MeshSpec.parse("data:8")))
    try:
        env = handle.server.environment
        assert env._mesh is not None, "--mesh did not attach a mesh"
        assert env._mesh.devices.size == 8
        assert env._min_bucket == 8  # batches pad to the data-axis size

        r = requests.post(
            handle.url("/validate/pod-privileged"),
            json=pod_review_body(True), timeout=60,
        )
        assert r.status_code == 200
        assert r.json()["response"]["allowed"] is False
        r = requests.post(
            handle.url("/validate/pod-privileged"),
            json=pod_review_body(False), timeout=60,
        )
        assert r.json()["response"]["allowed"] is True
    finally:
        handle.stop()


def test_policy_mesh_fused_spmd_serving():
    """--mesh data:4,policy:2 (default --mesh-dispatch fused) → ONE
    EvaluationEnvironment whose fused SPMD program spans the whole 2-D
    mesh: the policy axis is lax.switch branches + an all-gather inside
    one program, not threaded submesh dispatches."""
    metrics_mod.reset_metrics_for_tests()
    handle = ServerHandle(
        make_config(mesh=MeshSpec.parse("data:4,policy:2"))
    )
    try:
        env = handle.server.environment
        assert not isinstance(env, PolicyShardedEvaluator)
        assert env._mesh is not None
        assert env._mesh.devices.size == 8
        assert env._mesh_block is not None  # policy-sharded SPMD block
        assert env._min_bucket == 4  # batches pad to the DATA axis only

        before = env.host_profile["dispatched_chunks"]
        # verdicts through the real HTTP path, one device program each
        for pid, priv, expect in [
            ("pod-privileged", True, False),
            ("pod-privileged", False, True),
            ("group", False, True),
        ]:
            r = requests.post(
                handle.url(f"/validate/{pid}"),
                json=pod_review_body(priv), timeout=60,
            )
            assert r.status_code == 200, (pid, r.text)
            assert r.json()["response"]["allowed"] is expect, pid
        # unknown policy still 404s
        r = requests.post(
            handle.url("/validate/nope"), json=pod_review_body(False),
            timeout=60,
        )
        assert r.status_code == 404
    finally:
        handle.stop()


def test_policy_sharded_mesh_serving_threaded_fallback():
    """--mesh-dispatch threaded → the legacy MPMD PolicyShardedEvaluator
    (one fused program per policy shard, host thread-pool joins)."""
    metrics_mod.reset_metrics_for_tests()
    handle = ServerHandle(
        make_config(
            mesh=MeshSpec.parse("data:4,policy:2"),
            mesh_dispatch="threaded",
        )
    )
    try:
        env = handle.server.environment
        assert isinstance(env, PolicyShardedEvaluator)
        assert len(env.shards) == 2
        # every shard's fused program is data-parallel over its submesh row
        for shard in env.shards:
            assert shard._mesh is not None
            assert shard._mesh.devices.size == 4

        # verdicts route to the owning shard over the real HTTP path
        for pid, priv, expect in [
            ("pod-privileged", True, False),
            ("pod-privileged", False, True),
            ("group", False, True),
        ]:
            r = requests.post(
                handle.url(f"/validate/{pid}"),
                json=pod_review_body(priv), timeout=60,
            )
            assert r.status_code == 200, (pid, r.text)
            assert r.json()["response"]["allowed"] is expect, pid
        # unknown policy still 404s through the sharded router
        r = requests.post(
            handle.url("/validate/nope"), json=pod_review_body(False),
            timeout=60,
        )
        assert r.status_code == 404
    finally:
        handle.stop()


def test_default_auto_mesh_uses_all_devices():
    """The default 'auto' spec data-parallelizes over every visible device
    (TPU-first default: no flag needed to use the whole slice)."""
    metrics_mod.reset_metrics_for_tests()
    handle = ServerHandle(make_config())
    try:
        env = handle.server.environment
        assert env._mesh is not None
        assert env._mesh.devices.size == 8
    finally:
        handle.stop()
