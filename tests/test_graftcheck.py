"""graftcheck suite tests: golden fixtures per checker (every seeded
violation flagged, every clean fixture silent), the 3-lock ABC/BCA
cycle detector, the lock-order sanitizer's runtime graph, the baseline
mechanics, and the repo itself passing the gate. Plus the round-8
concurrency-fix regression tests (one per fix)."""

from __future__ import annotations

import sys
import threading
import time
from pathlib import Path

import pytest

REPO_ROOT = Path(__file__).resolve().parents[1]
if str(REPO_ROOT) not in sys.path:
    sys.path.insert(0, str(REPO_ROOT))

from tools.graftcheck import concurrency, failpoint_drift, observability, tracepurity  # noqa: E402
from tools.graftcheck.base import Finding, apply_baseline, load_baseline  # noqa: E402

FIXTURES = Path(__file__).parent / "graftcheck_fixtures"


def rules_of(findings: list[Finding]) -> set[str]:
    return {f.rule for f in findings}


def symbols_of(findings: list[Finding], rule: str) -> set[str]:
    return {f.symbol for f in findings if f.rule == rule}


# ---------------------------------------------------------------------------
# Checker 1 — concurrency
# ---------------------------------------------------------------------------


def test_guarded_by_violation_fixture_flagged():
    findings = concurrency.check(FIXTURES / "gb_violation", "pkg")
    assert rules_of(findings) == {"GB01"}
    syms = symbols_of(findings, "GB01")
    assert "Counter.racy_read:value" in syms
    assert "Counter.racy_check_then_set:value" in syms
    # annotated MODULE GLOBALS are enforced too, not just attributes
    assert "racy_global_read:_registry" in syms
    assert not any("register:" in s for s in syms)  # locked writer clean
    # the lockfree-annotated attribute is never flagged
    assert not any("snapshot" in s for s in syms)


def test_guarded_by_clean_fixture_passes():
    assert concurrency.check(FIXTURES / "gb_clean", "pkg") == []


def test_lock_order_abc_bca_cycle_flagged():
    findings = concurrency.check(FIXTURES / "lo_cycle_abc", "pkg")
    cycles = [f for f in findings if f.rule == "LO01"]
    assert len(cycles) == 1
    # all three locks participate in the reported cycle
    msg = cycles[0].message
    for lock in ("_a", "_b", "_c"):
        assert f"Router.{lock}" in msg


def test_lock_order_clean_fixture_passes():
    assert concurrency.check(FIXTURES / "lo_clean", "pkg") == []


# ---------------------------------------------------------------------------
# Checker 2 — trace purity
# ---------------------------------------------------------------------------


def test_trace_purity_violations_flagged():
    findings = tracepurity.check(FIXTURES / "tp_violation", "pkg")
    rules = rules_of(findings)
    assert {"TP01", "TP02", "TP03"} <= rules
    # TP01 fires in the helper REACHED from the jit root, not just the root
    assert any(
        f.rule == "TP01" and "_impure_helper" in f.symbol for f in findings
    )
    assert any(
        f.rule == "TP03" and "sneaky_fetch" in f.symbol for f in findings
    )


def test_trace_purity_clean_fixture_passes():
    assert tracepurity.check(FIXTURES / "tp_clean", "pkg") == []


# ---------------------------------------------------------------------------
# Checker 3 — observability
# ---------------------------------------------------------------------------


def test_observability_fixture_flags_every_seeded_drift():
    findings = observability.check(
        FIXTURES / "obs",
        metrics_path="metrics_fix.py",
        server_path="server_fix.py",
        dashboard_path="dash.json",
        environment_path="env_fix.py",
    )
    rules = rules_of(findings)
    assert {"OB01", "OB02", "OB03", "OB04", "OB05", "OB06", "OB07"} <= rules
    # both OB01 shapes: a literal name AND a computed-name expression
    assert any(
        f.rule == "OB01" and "fixture_literal" in f.symbol for f in findings
    )
    assert any(
        f.rule == "OB01" and "computed" in f.symbol for f in findings
    )
    assert any(
        f.rule == "OB03" and "DEAD_METRIC" in f.symbol for f in findings
    )
    assert any(
        f.rule == "OB04" and "fixture_depth" in f.symbol for f in findings
    )
    assert any(
        f.rule == "OB05" and "ghost" in f.symbol for f in findings
    )
    assert any(
        f.rule == "OB06" and "policy_mode" in f.symbol for f in findings
    )
    # OB07: uncovered stats keys flagged, the covered one not
    ob07 = [f for f in findings if f.rule == "OB07"]
    assert any("phantom_stat" in f.symbol for f in ob07)
    assert any("ghost_kernel_stat" in f.symbol for f in ob07)
    assert not any("covered_stat" in f.symbol for f in ob07)


def test_observability_repo_mapping_is_total():
    """Acceptance: the live counter<->OTLP<->dashboard mapping has no
    unexported increments, no dead instruments, no dead panels."""
    assert observability.check(REPO_ROOT) == []


def test_ob08_phase_violation_fixture_flagged():
    """OB08 (round 18): an unstamped phase, a double-stamped phase, and
    a histogram family with no dashboard panel are all flagged; the
    once-stamped phase is not."""
    findings = observability.check(
        FIXTURES / "obs_phase_violation",
        metrics_path="metrics_fix.py",
        server_path="server_fix.py",
        dashboard_path="dash.json",
        flightrec_path="flightrec_fix.py",
        package_path="pkg",
    )
    ob08 = [f for f in findings if f.rule == "OB08"]
    assert any("phase:unstamped:gamma" == f.symbol for f in ob08)
    assert any("phase:multi:beta" == f.symbol for f in ob08)
    assert any(
        "histogram:policy_server_fixture_phase_seconds" == f.symbol
        for f in ob08
    )
    assert not any("alpha" in f.symbol for f in ob08)


def test_ob08_phase_clean_fixture_passes():
    findings = observability.check(
        FIXTURES / "obs_phase_clean",
        metrics_path="metrics_fix.py",
        server_path="server_fix.py",
        dashboard_path="dash.json",
        flightrec_path="flightrec_fix.py",
        package_path="pkg",
    )
    assert [f for f in findings if f.rule == "OB08"] == []


# ---------------------------------------------------------------------------
# Checker 4 — failpoint drift
# ---------------------------------------------------------------------------


def test_failpoint_drift_fixture_flagged():
    findings = failpoint_drift.check(
        FIXTURES / "fp_drift",
        package="pkg",
        tests_dir="tests",
        failpoints_rel="does/not/exist.py",
    )
    assert rules_of(findings) == {"FP01", "FP02", "FP04"}
    assert symbols_of(findings, "FP01") == {"armed:site.phantom"}
    assert symbols_of(findings, "FP02") == {"fired:site.unarmed"}
    # site.armed is armed ONLY by a plain unit-test file; site.chaosed
    # is armed from a test_resilience* file and stays FP04-clean
    assert symbols_of(findings, "FP04") == {"unchaosed:site.armed"}


def test_failpoint_repo_sites_all_armed_and_documented():
    assert failpoint_drift.check(REPO_ROOT) == []


# ---------------------------------------------------------------------------
# Checker — state-dir write discipline (FS01, round 17)
# ---------------------------------------------------------------------------


def test_statestore_fs_violation_fixture_flagged():
    from tools.graftcheck import statestore_fs

    findings = statestore_fs.check(FIXTURES / "fs_violation", "pkg")
    assert rules_of(findings) == {"FS01"}
    by_file = {(f.path, f.line) for f in findings}
    # the three raw writes in the statestore module outside the
    # annotated helper: open("wb"), Path.write_text, os.rename
    assert ("pkg/statestore.py", 15) in by_file
    assert ("pkg/statestore.py", 20) in by_file
    assert ("pkg/statestore.py", 24) in by_file
    # the package-wide rule: another module writing into the state dir
    assert ("pkg/other.py", 6) in by_file
    # the annotated helper's own writes and plain reads are clean, and
    # other modules' non-state-dir writes are not this checker's business
    assert len(findings) == 4


def test_statestore_fs_clean_fixture_passes():
    from tools.graftcheck import statestore_fs

    assert statestore_fs.check(FIXTURES / "fs_clean", "pkg") == []


def test_statestore_fs_repo_clean():
    """FS01 over the real tree: every state-dir write goes through the
    atomic helper (baseline stays empty)."""
    from tools.graftcheck import statestore_fs

    assert statestore_fs.check(REPO_ROOT) == []


# ---------------------------------------------------------------------------
# Baseline mechanics
# ---------------------------------------------------------------------------


def test_baseline_suppresses_and_reports_stale(tmp_path):
    f = Finding("concurrency", "GB01", "a.py", 3, "C.m:x", "boom")
    baseline = {f.fingerprint: "known dirty read", "GB01:gone.py:C.m:y": "stale"}
    res = apply_baseline([f], baseline)
    assert res.new == []
    assert [s[0] for s in res.suppressed] == [f]
    assert res.stale == ["GB01:gone.py:C.m:y"]
    # fingerprints are line-number-free: moving the finding keeps the match
    f2 = Finding("concurrency", "GB01", "a.py", 99, "C.m:x", "boom")
    assert f2.fingerprint == f.fingerprint


def test_repo_concurrency_and_tracepurity_clean():
    """The round-8 audit fixed or annotated everything the suite finds in
    the current tree, so the checkers run clean with an EMPTY baseline."""
    assert concurrency.check(REPO_ROOT) == []
    assert tracepurity.check(REPO_ROOT) == []
    assert load_baseline(REPO_ROOT / "tools/graftcheck/baseline.json") == {}


# ---------------------------------------------------------------------------
# Checker 5 — lock-order sanitizer (runtime)
# ---------------------------------------------------------------------------


def _fresh_locksan():
    from policy_server_tpu import locksan

    if locksan.installed():
        # an armed session (make chaos) owns the global state; these
        # synthetic-graph tests would pollute its report
        pytest.skip("locksan armed session: synthetic graph tests skipped")
    return locksan


def test_locksan_detects_abc_bca_inversion():
    locksan = _fresh_locksan()
    locksan.reset()
    a = locksan.SanLock(threading.Lock(), "fix.py:1", False)
    b = locksan.SanLock(threading.Lock(), "fix.py:2", False)
    c = locksan.SanLock(threading.Lock(), "fix.py:3", False)
    with a, b:
        pass
    with b, c:
        pass
    with c, a:  # closes the 3-cycle
        pass
    rep = locksan.report()
    assert rep["inversions"] == [["fix.py:1", "fix.py:2", "fix.py:3"]]
    assert rep["acquisitions"] == 6
    # the first-seen stacks are attached for the report
    assert rep["inversion_stacks"]
    locksan.reset()


def test_locksan_consistent_order_is_clean_and_same_site_ignored():
    locksan = _fresh_locksan()
    locksan.reset()
    a = locksan.SanLock(threading.Lock(), "fix.py:1", False)
    b = locksan.SanLock(threading.Lock(), "fix.py:2", False)
    b2 = locksan.SanLock(threading.Lock(), "fix.py:2", False)
    with a, b:
        pass
    with b, b2:  # same creation site: hand-over-hand, no edge
        pass
    rep = locksan.report()
    assert rep["inversions"] == []
    assert rep["edges"] == [("fix.py:1", "fix.py:2")]
    locksan.reset()


def test_locksan_long_hold_reported():
    locksan = _fresh_locksan()
    locksan.reset()
    old = locksan.HOLD_THRESHOLD_MS
    locksan.HOLD_THRESHOLD_MS = 5.0
    try:
        lk = locksan.SanLock(threading.Lock(), "fix.py:9", False)
        with lk:
            time.sleep(0.02)
        rep = locksan.report()
        assert rep["long_holds"] and rep["long_holds"][0][0] == "fix.py:9"
        assert rep["inversions"] == []  # long holds report, never fail
    finally:
        locksan.HOLD_THRESHOLD_MS = old
        locksan.reset()


def test_locksan_install_instruments_package_locks_only():
    locksan = _fresh_locksan()
    locksan.install()
    try:
        from policy_server_tpu.resilience import CircuitBreaker

        breaker = CircuitBreaker()
        assert type(breaker._lock).__name__ == "SanLock"
        # non-package construction sites keep native locks
        assert type(threading.Lock()).__name__ != "SanLock"
        breaker.record_failure()
        assert breaker.state  # instrumented lock drives the real breaker
    finally:
        locksan.uninstall()
        locksan.reset()


# ---------------------------------------------------------------------------
# Round-8 concurrency-fix regressions (one per fix)
# ---------------------------------------------------------------------------


def test_verdict_cache_len_and_bytes_consistent_under_concurrent_puts():
    """Fix: __len__/bytes_used read _data/_bytes under _lock (they raced
    _put_locked's pop/reinsert+eviction before round 8)."""
    from policy_server_tpu.evaluation.verdict_cache import VerdictCache

    cache = VerdictCache(capacity_bytes=64 * 1024)
    stop = threading.Event()
    errors: list[BaseException] = []

    def writer(tag: str):
        i = 0
        try:
            while not stop.is_set():
                cache.put_many(
                    [((tag, i, j), {"v": j, "w": j + 1}) for j in range(16)]
                )
                i += 1
        except BaseException as e:  # noqa: BLE001
            errors.append(e)

    def reader():
        try:
            while not stop.is_set():
                n = len(cache)
                used = cache.bytes_used
                assert n >= 0 and used >= 0
        except BaseException as e:  # noqa: BLE001
            errors.append(e)

    threads = [
        threading.Thread(target=writer, args=("a",)),
        threading.Thread(target=writer, args=("b",)),
        threading.Thread(target=reader),
        threading.Thread(target=reader),
    ]
    for t in threads:
        t.start()
    time.sleep(0.3)
    stop.set()
    for t in threads:
        t.join(timeout=5)
    assert not errors
    # post-quiescence invariant: accounted bytes match the entries
    with cache._lock:
        assert cache._bytes == sum(c for _row, c in cache._data.values())
    assert cache.bytes_used <= cache.capacity_bytes


def test_otlp_span_drop_counter_exact_under_concurrent_on_end():
    """Fix: BatchSpanProcessor.dropped += was an unlocked read-modify-write
    racing every request thread; with the lock the count is exact. Every
    on_end either queues the span or counts a drop, and queued spans are
    either exported or still resident — so dropped must equal
    total - exported - queued EXACTLY; a lost update breaks the identity."""
    from policy_server_tpu.telemetry import otlp

    class _CountingExporter:
        def __init__(self):
            self.exported = 0
            self._lock = threading.Lock()

        def export_spans(self, spans):
            with self._lock:
                self.exported += len(spans)
            return True

    exporter = _CountingExporter()
    proc = otlp.BatchSpanProcessor(
        exporter, interval_seconds=3600, max_batch=4, max_queue=4
    )
    try:
        span = otlp.SpanData("s", b"t" * 16, b"s" * 8, b"", 0, 1)
        n_threads, per_thread = 8, 200
        total = n_threads * per_thread
        barrier = threading.Barrier(n_threads)

        def spam():
            barrier.wait()
            for _ in range(per_thread):
                proc.on_end(span)

        threads = [threading.Thread(target=spam) for _ in range(n_threads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=10)
        # settle: the flusher may be mid-drain; wait for the accounting
        # to go stable before asserting exactness
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            before = (proc.dropped, exporter.exported, proc._queue.qsize())
            time.sleep(0.05)
            after = (proc.dropped, exporter.exported, proc._queue.qsize())
            if before == after:
                break
        assert proc.dropped + exporter.exported + proc._queue.qsize() == total
        assert proc.dropped > 0  # the 4-deep queue must have overflowed
    finally:
        proc.shutdown()


def test_breaker_stats_consistent_under_concurrent_short_circuits():
    """Fix: breaker_stats/dedup_stats read their _fallback_lock-guarded
    counters under the lock (dirty reads before round 8)."""
    from policy_server_tpu.resilience import CircuitBreaker

    breaker = CircuitBreaker(failure_threshold=1, cooldown_seconds=60.0)
    breaker.record_failure()  # trip it
    assert breaker.state == "open"
    results: list[dict] = []
    errors: list[BaseException] = []

    def hammer():
        try:
            for _ in range(500):
                breaker.allow_device()
                results.append(breaker.stats())
        except BaseException as e:  # noqa: BLE001
            errors.append(e)

    threads = [threading.Thread(target=hammer) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=10)
    assert not errors
    for s in results:
        assert s["open"] == 1 and s["trips"] == 1
    # per-call denials were counted exactly (lock-guarded increment)
    assert breaker.short_circuits == 4 * 500


# ---------------------------------------------------------------------------
# Checker — native/Python response-shape totality (RS01/RS02, round 19)
# ---------------------------------------------------------------------------


def test_respshape_violation_fixture_flagged():
    """RS01: an unclassified to_dict field AND a stale classification
    entry; RS02: emitter key order diverging from to_dict."""
    from tools.graftcheck import respshape

    findings = respshape.check(
        FIXTURES / "rs_violation",
        models_path="models_fix.py",
        frontend_path="frontend_fix.py",
        csrc_path="csrc_fix.cpp",
    )
    syms = {f.symbol for f in findings}
    assert "unclassified:AdmissionResponse.priority" in syms
    assert "stale:AdmissionResponse.patch" in syms
    # the fixture's C++ emits code before message
    assert "order:ValidationStatus.code" in syms


def test_respshape_clean_fixture_passes():
    from tools.graftcheck import respshape

    assert respshape.check(
        FIXTURES / "rs_clean",
        models_path="models_fix.py",
        frontend_path="frontend_fix.py",
        csrc_path="csrc_fix.cpp",
    ) == []


def test_respshape_repo_classification_is_total():
    """Acceptance: the live native serializer's field classification is
    total over the response models and the C++ emitter's key order
    matches to_dict's."""
    from tools.graftcheck import respshape

    assert respshape.check(REPO_ROOT) == []


# ---------------------------------------------------------------------------
# Checkers 8/9 — native ABI drift + wire-parser bounds (round 21)
# ---------------------------------------------------------------------------


def test_native_abi_drift_fixture_flags_every_seeded_violation():
    from tools.graftcheck import native_abi

    d = FIXTURES / "na_drift"
    findings = native_abi.check(
        d, csrc_paths=[d / "csrc_fix.cpp"], py_paths=[d / "binding_fix.py"]
    )
    assert rules_of(findings) == {"NA01", "NA02", "NA03"}
    # NA01: phantom binding, incompatible argtype, missing 64-bit restype
    assert symbols_of(findings, "NA01") == {
        "nat_missing", "nat_poll:arg2", "nat_poll:restype",
    }
    # NA02: drifted anchored layout + unanchored packed struct
    assert symbols_of(findings, "NA02") == {"abi:NatHdr", "abi:Orphan"}
    # NA03: inline wire-format literal
    assert symbols_of(findings, "NA03") == {"inline-fmt:<I"}


def test_native_abi_clean_fixture_passes():
    """Struct-mode AND offsets-mode anchors resolve with zero findings
    when both sides agree."""
    from tools.graftcheck import native_abi

    c = FIXTURES / "na_clean"
    assert native_abi.check(
        c, csrc_paths=[c / "csrc_fix.cpp"], py_paths=[c / "binding_fix.py"]
    ) == []


def test_native_bounds_violation_fixture_flags_every_seeded_violation():
    from tools.graftcheck import native_bounds

    v = FIXTURES / "nw_violation"
    findings = native_bounds.check(v, csrc_paths=[v / "csrc_fix.cpp"])
    assert rules_of(findings) == {"NW01", "NW02", "NW03"}
    assert symbols_of(findings, "NW01") == {"parse_rec:n:resize"}
    assert symbols_of(findings, "NW02") == {"banned:strcpy"}
    assert symbols_of(findings, "NW03") == {"header_len:narrow:out.size()"}


def test_native_bounds_clean_fixture_passes():
    """Range checks, the take() lambda idiom, snprintf, a dominating
    size check, and the bounds-ok escape all clear the lint."""
    from tools.graftcheck import native_bounds

    c = FIXTURES / "nw_clean"
    assert native_bounds.check(c, csrc_paths=[c / "csrc_fix.cpp"]) == []


def test_native_checkers_repo_clean_and_armed():
    """Acceptance: both native checkers run clean on the live tree with
    an EMPTY baseline, and the bounds lint is armed (NW00 would fire if
    csrc/httpfront.cpp lost its wire-input annotations)."""
    from tools.graftcheck import native_abi, native_bounds

    assert native_abi.check(REPO_ROOT) == []
    assert native_bounds.check(REPO_ROOT) == []
    assert load_baseline(REPO_ROOT / "tools/graftcheck/baseline.json") == {}


def test_native_abi_stale_baseline_fails():
    """A baseline entry naming a fixed NA finding is reported stale —
    the suppression cannot outlive the bug."""
    from tools.graftcheck import native_abi

    d = FIXTURES / "na_drift"
    findings = native_abi.check(
        d, csrc_paths=[d / "csrc_fix.cpp"], py_paths=[d / "binding_fix.py"]
    )
    baseline = {
        "NA01:binding_fix.py:nat_missing": "known, tracked",
        "NA02:gone.cpp:abi:Retired": "fixed two rounds ago",
    }
    res = apply_baseline(findings, baseline)
    assert res.stale == ["NA02:gone.cpp:abi:Retired"]
    suppressed = {s[0].symbol for s in res.suppressed}
    assert suppressed == {"nat_missing"}
    assert {f.symbol for f in res.new} == {
        "nat_poll:arg2", "nat_poll:restype",
        "abi:NatHdr", "abi:Orphan", "inline-fmt:<I",
    }
