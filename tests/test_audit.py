"""Background audit scanner (round 10): snapshot-store mechanics, the
micro-batcher's best-effort audit lane (idle-only dispatch, single
in-flight cap, preemption), the sweep pipeline (full / dirty / breaker
pause / fault abort+resume), epoch coherence (promote → full re-scan,
rollback → stale reports), the GET /audit/reports surface, and the
audit-vs-validate constraint-skip pin (reference handlers.rs:69-90)."""

from __future__ import annotations

import base64
import json
import threading
import time
from types import SimpleNamespace

import pytest

from policy_server_tpu import failpoints
from policy_server_tpu.audit import (
    AuditScanner,
    PolicyReportStore,
    SnapshotStore,
    resource_key,
)
from policy_server_tpu.api.service import RequestOrigin
from policy_server_tpu.evaluation.environment import (
    EvaluationEnvironmentBuilder,
)
from policy_server_tpu.models import (
    AdmissionResponse,
    AdmissionReviewRequest,
    ValidateRequest,
)
from policy_server_tpu.models.policy import parse_policy_entry
from policy_server_tpu.runtime.batcher import DEADLINE_MESSAGE, MicroBatcher
from policy_server_tpu.telemetry import metrics as metrics_mod

from conftest import build_admission_review_dict


@pytest.fixture(autouse=True)
def fresh_metrics():
    metrics_mod.reset_metrics_for_tests()
    yield
    metrics_mod.reset_metrics_for_tests()


@pytest.fixture(autouse=True)
def clean_failpoints():
    failpoints.reset()
    yield
    failpoints.reset()


def pod_review(
    name: str = "p",
    namespace: str = "default",
    privileged: bool = False,
    operation: str = "CREATE",
) -> ValidateRequest:
    doc = build_admission_review_dict()
    doc["request"]["uid"] = f"uid-{namespace}-{name}"
    doc["request"]["name"] = name
    doc["request"]["namespace"] = namespace
    doc["request"]["operation"] = operation
    doc["request"]["kind"] = {"group": "", "version": "v1", "kind": "Pod"}
    doc["request"]["object"] = {
        "apiVersion": "v1",
        "kind": "Pod",
        "metadata": {"name": name, "namespace": namespace},
        "spec": {
            "containers": [
                {
                    "name": "c",
                    "image": "nginx",
                    "securityContext": {"privileged": privileged},
                }
            ]
        },
    }
    return ValidateRequest.from_admission(
        AdmissionReviewRequest.from_dict(doc).request
    )


# ---------------------------------------------------------------------------
# snapshot store
# ---------------------------------------------------------------------------


def test_snapshot_key_supersede_delete_and_dirty():
    store = SnapshotStore(max_bytes=10 * 1024 * 1024)
    a1 = pod_review("a", privileged=False)
    a2 = pod_review("a", privileged=True)  # same object, newer admission
    b = pod_review("b")
    assert resource_key(a1) == resource_key(a2)
    store.observe([a1, b])
    assert len(store) == 2
    # later admission supersedes the earlier snapshot of the same object
    store.observe([a2])
    assert len(store) == 2
    rows = dict(store.collect())
    assert rows[resource_key(a2)] is a2
    # collect cleared the dirty set; a fresh observe re-dirties only "a"
    store.observe([a2])
    dirty = store.collect(dirty_only=True)
    assert [k for k, _ in dirty] == [resource_key(a2)]
    # DELETE evicts the object from the snapshot
    store.observe([pod_review("a", operation="DELETE")])
    assert len(store) == 1
    stats = store.stats()
    # two supersedes: a2 over a1, then the re-observe of a2 over itself
    assert stats["superseded"] == 2 and stats["deleted"] == 1
    # raw requests are untrackable and ignored
    store.observe([ValidateRequest.from_raw({"uid": "r"})])
    assert len(store) == 1


def test_snapshot_byte_budget_evicts_lru():
    one = len(pod_review("x").payload_json())
    store = SnapshotStore(max_bytes=int(one * 2.5))
    store.observe([pod_review(f"n{i}") for i in range(4)])
    assert len(store) == 2  # only the 2 newest fit the budget
    assert store.stats()["evicted"] == 2
    assert store.stats()["bytes"] <= int(one * 2.5)
    kept = [k for k, _ in store.collect()]
    assert all(k.endswith(("n2", "n3")) for k in kept)


def test_snapshot_seed_from_file(tmp_path):
    path = tmp_path / "resources.yml"
    path.write_text(
        json.dumps(
            {
                "items": [
                    {
                        "apiVersion": "v1",
                        "kind": "Pod",
                        "metadata": {"name": "seeded", "namespace": "ns1"},
                        "spec": {"containers": [{"name": "c", "image": "i"}]},
                    },
                    {"not-an-object": True},
                ]
            }
        )
    )
    store = SnapshotStore()
    assert store.seed_from_file(str(path)) == 1
    (key, req), = store.collect()
    assert key == "/v1/Pod/ns1/seeded"
    assert req.admission_request.operation == "CREATE"


# ---------------------------------------------------------------------------
# report store
# ---------------------------------------------------------------------------


def test_report_store_rows_summary_and_rollback_staleness():
    store = PolicyReportStore()
    req = pod_review("a", namespace="ns1")
    key = resource_key(req)
    deny = AdmissionResponse.reject("u", "denied", 400)
    allow = AdmissionResponse(uid="u", allowed=True)
    store.put([
        store.row_from_result(key, "p1", req, deny, epoch=0),
        store.row_from_result(key, "p2", req, allow, epoch=0),
        store.row_from_result(key, "p3", req, RuntimeError("boom"), epoch=0),
    ])
    body = store.payload()
    assert body["summary"] == {
        # the error row carries allowed=None: neither pass nor fail
        "results": 3, "resources": 1, "pass": 1, "fail": 1, "error": 1,
        "mutated": 0, "stale": 0,
    }
    # namespace filter
    assert store.payload("other")["summary"]["results"] == 0
    assert store.payload("ns1")["summary"]["results"] == 3
    # a re-scan under epoch 1 overwrites per (resource, policy)
    store.put([store.row_from_result(key, "p1", req, allow, epoch=1)])
    assert store.payload()["summary"]["results"] == 3
    # rollback of epoch 1 marks exactly its rows stale; stale rows drop
    # out of pass/fail but stay listed
    assert store.mark_epoch_stale(1) == 1
    body = store.payload()
    assert body["summary"]["stale"] == 1
    assert body["summary"]["pass"] == 1  # p2's epoch-0 allow
    stale_rows = [r for r in body["reports"] if r["stale"]]
    assert [r["policy_id"] for r in stale_rows] == ["p1"]


# ---------------------------------------------------------------------------
# the batcher's best-effort audit lane
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def env():
    policies = {
        "priv": parse_policy_entry(
            "priv", {"module": "builtin://pod-privileged"}
        ),
        "happy": parse_policy_entry(
            "happy", {"module": "builtin://always-happy"}
        ),
    }
    e = EvaluationEnvironmentBuilder(backend="jax").build(policies)
    yield e
    e.close()


def test_audit_lane_dispatches_raw_verdicts_when_idle(env):
    batcher = MicroBatcher(env, max_batch_size=8, policy_timeout=10.0).start()
    try:
        pairs = [
            ("priv", pod_review("lane-a", privileged=True)),
            ("priv", pod_review("lane-b", privileged=False)),
        ]
        results = batcher.submit_audit(pairs).result(timeout=30)
        assert results[0].allowed is False
        assert results[1].allowed is True
        snap = batcher.stats_snapshot()
        assert snap["audit_batches_dispatched"] == 1
        assert snap["audit_rows_dispatched"] == 2
        assert batcher.audit_lane_depth() == 0
    finally:
        batcher.shutdown()


def test_audit_lane_single_inflight_cap(env):
    """Two audit jobs with a blocked dispatch: the second must not start
    until the first finishes — the lane's in-flight cap is exactly 1."""
    release = threading.Event()
    started: list[float] = []
    real = env.validate_batch

    class Blocking:
        def __getattr__(self, name):
            return getattr(env, name)

        def validate_batch(self, pairs, **kw):
            started.append(time.perf_counter())
            assert release.wait(timeout=30)
            return real(pairs, **kw)

    batcher = MicroBatcher(
        Blocking(), max_batch_size=8, policy_timeout=10.0
    ).start()
    try:
        f1 = batcher.submit_audit([("happy", pod_review("c1"))])
        f2 = batcher.submit_audit([("happy", pod_review("c2"))])
        deadline = time.perf_counter() + 5
        while not started and time.perf_counter() < deadline:
            time.sleep(0.01)
        assert len(started) == 1  # second job waits for the slot
        time.sleep(0.3)
        assert len(started) == 1
        release.set()
        assert f1.result(timeout=30)[0].allowed is True
        assert f2.result(timeout=30)[0].allowed is True
        assert len(started) == 2
    finally:
        release.set()
        batcher.shutdown()


def test_audit_preemption_requeues_for_live_work(env):
    """A popped audit job observing live work re-queues itself at the
    lane head and counts a preemption (driven synchronously: the
    dispatch loop is not running, so the race window is forced)."""
    batcher = MicroBatcher(env, max_batch_size=8, policy_timeout=10.0)
    # NOT started: we drive the lane by hand
    fut = batcher.submit_audit([("happy", pod_review("pre"))])
    # live work arrives
    live = batcher.submit("happy", pod_review("live"), RequestOrigin.VALIDATE)
    batcher._maybe_dispatch_audit()
    deadline = time.perf_counter() + 5
    while batcher.audit_lane_depth() == 0 and time.perf_counter() < deadline:
        time.sleep(0.01)
    assert batcher.audit_lane_depth() == 1  # re-queued, not dispatched
    assert batcher.stats_snapshot()["audit_preemptions"] == 1
    assert not fut.done()
    # once the live lane drains (started loop), both complete
    batcher.start()
    assert live.result(timeout=30).allowed is True
    assert fut.result(timeout=30)[0].allowed is True
    batcher.shutdown()


def test_audit_slack_gate_blocks_on_breaker_and_tight_budget(env):
    class BreakerOpen:
        breaker_all_open = True

        def __getattr__(self, name):
            return getattr(env, name)

    batcher = MicroBatcher(BreakerOpen(), max_batch_size=8)
    assert batcher._audit_slack_ok(8) is False
    # slack keys on the HARD request-deadline budget (the soft latency
    # budget defends itself via the host-side router instead)
    batcher2 = MicroBatcher(
        env, max_batch_size=8, request_timeout_ms=100.0,
    )
    from policy_server_tpu.evaluation.environment import bucket_size

    batcher2._dev_rtt[bucket_size(8)] = 0.5  # 500 ms RTT >> 100 ms budget
    assert batcher2._audit_slack_ok(8) is False
    batcher2._dev_rtt[bucket_size(8)] = 0.001
    assert batcher2._audit_slack_ok(8) is True
    # the hold estimate scales with the AUDIT batch size, not the live
    # bucket alone: 8 ms/chunk x 64 rows / 8-row bucket = 64 ms > 50 ms
    batcher2._dev_rtt[bucket_size(8)] = 0.008
    assert batcher2._audit_slack_ok(8) is True
    assert batcher2._audit_slack_ok(64) is False
    # no deadline propagation configured: audit always has slack when idle
    batcher3 = MicroBatcher(env, max_batch_size=8, request_timeout_ms=0.0)
    batcher3._dev_rtt[bucket_size(8)] = 0.5
    assert batcher3._audit_slack_ok(8) is True


def test_audit_lane_rejects_on_shutdown(env):
    batcher = MicroBatcher(env, max_batch_size=8)
    fut = batcher.submit_audit([("happy", pod_review("s1"))])
    batcher.shutdown()
    with pytest.raises(RuntimeError, match="audit lane closed"):
        fut.result(timeout=5)
    with pytest.raises(RuntimeError, match="audit lane closed"):
        batcher.submit_audit([("happy", pod_review("s2"))]).result(timeout=5)


def test_preemption_proof_live_deadlines_met_under_saturating_sweep(env):
    """THE acceptance property: with the audit lane saturated (far more
    queued audit rows than the device can absorb), injected live
    requests still meet their deadline — a live batch never waits behind
    more than the single in-flight audit dispatch."""
    batcher = MicroBatcher(
        env, max_batch_size=16, policy_timeout=5.0,
        host_fastpath_threshold=0,  # live rides the device path too
    ).start()
    try:
        batcher.warmup()
        # saturate: 40 audit batches x 64 unique rows, far beyond what
        # dispatches during the test
        for b in range(40):
            batcher.submit_audit([
                ("priv", pod_review(f"audit-{b}-{i}", privileged=bool(i % 2)))
                for i in range(64)
            ])
        latencies: list[float] = []
        for wave in range(10):
            t0 = time.perf_counter()
            futs = [
                batcher.submit(
                    "priv", pod_review(f"live-{wave}-{i}", privileged=False),
                    RequestOrigin.VALIDATE,
                )
                for i in range(8)
            ]
            for f in futs:
                resp = f.result(timeout=10)
                assert resp.allowed is True, resp.status
                if resp.status is not None:
                    assert resp.status.message != DEADLINE_MESSAGE
            latencies.append(time.perf_counter() - t0)
            time.sleep(0.05)  # idle gap: the audit lane may claim it
        snap = batcher.stats_snapshot()
        # audit throughput rode the idle gaps...
        assert snap["audit_batches_dispatched"] >= 1
        # ...while every live wave stayed far inside the 5 s deadline
        # (one in-flight audit dispatch of 64 rows bounds the wait)
        assert max(latencies) < 4.0, latencies
        assert snap["deadline_abandoned_batches"] == 0
    finally:
        batcher.shutdown()


# ---------------------------------------------------------------------------
# the scanner
# ---------------------------------------------------------------------------


def make_scanner(env, batcher, lifecycle=None, **kw):
    state = SimpleNamespace(
        evaluation_environment=env, batcher=batcher, lifecycle=lifecycle
    )
    snapshot = SnapshotStore()
    reports = PolicyReportStore()
    kw.setdefault("mode", "interval")
    kw.setdefault("interval_seconds", 30.0)
    scanner = AuditScanner(
        state=state, snapshot=snapshot, reports=reports, **kw
    )
    return scanner


def test_scanner_full_and_dirty_sweeps(env):
    batcher = MicroBatcher(env, max_batch_size=8, policy_timeout=10.0).start()
    scanner = make_scanner(env, batcher, batch_size=4)
    try:
        scanner.snapshot.observe([
            pod_review("a", privileged=True), pod_review("b"),
        ])
        # full sweep: 2 resources x 2 policies = 4 rows
        assert scanner.sweep(full=True) == 4
        body = scanner.report_payload()
        assert body["summary"]["results"] == 4
        assert body["summary"]["resources"] == 2
        # "a" is privileged: priv denies it, happy allows everything
        by = {(r["name"], r["policy_id"]): r for r in body["reports"]}
        assert by[("a", "priv")]["allowed"] is False
        assert by[("a", "happy")]["allowed"] is True
        assert by[("b", "priv")]["allowed"] is True
        assert all(r["epoch"] == 0 for r in body["reports"])
        # nothing dirty: a dirty sweep scans nothing
        assert scanner.sweep(full=False) == 0
        # touch one object: the dirty sweep re-judges only it
        scanner.snapshot.observe([pod_review("b", privileged=True)])
        assert scanner.sweep(full=False) == 2
        body = scanner.report_payload()
        by = {(r["name"], r["policy_id"]): r for r in body["reports"]}
        assert by[("b", "priv")]["allowed"] is False  # superseded object
        stats = scanner.stats()
        assert stats["full_sweeps"] == 1
        assert stats["dirty_sweeps"] == 2
        assert stats["rows_scanned"] == 6
        assert stats["freshness_seconds"] >= 0
    finally:
        batcher.shutdown()


def test_scanner_rows_scanned_accounts_whole_run_across_epochs(env):
    """PROFILE r13 caveat 3 (soak-artifact accounting): ``rows_scanned``
    is the WHOLE-RUN total across policy epochs, and
    ``rows_scanned_by_epoch`` decomposes it — a run whose last event is
    an epoch flip reports every epoch's audit volume, not only the
    post-promote sweep's."""
    lifecycle = SimpleNamespace(current_epoch=0)
    batcher = MicroBatcher(env, max_batch_size=8, policy_timeout=10.0).start()
    scanner = make_scanner(env, batcher, lifecycle=lifecycle, batch_size=4)
    try:
        scanner.snapshot.observe([
            pod_review("a", privileged=True), pod_review("b"),
        ])
        assert scanner.sweep(full=True) == 4  # epoch 0
        # promote: the post-promote full sweep re-judges everything
        # under the new epoch's set
        lifecycle.current_epoch = 1
        scanner.on_promote(1)
        assert scanner.sweep(full=True) == 4  # epoch 1
        stats = scanner.stats()
        assert stats["rows_scanned"] == 8  # whole run, both epochs
        assert stats["rows_scanned_by_epoch"] == {"0": 4, "1": 4}
        assert (
            sum(stats["rows_scanned_by_epoch"].values())
            == stats["rows_scanned"]
        )
    finally:
        batcher.shutdown()


def test_scanner_pauses_while_breaker_open(env):
    class BreakerOpen:
        breaker_all_open = True

        def __getattr__(self, name):
            return getattr(env, name)

    batcher = MicroBatcher(env, max_batch_size=8).start()
    scanner = make_scanner(BreakerOpen(), batcher)
    try:
        scanner.snapshot.observe([pod_review("a")])
        assert scanner.sweep(full=True) == 0
        assert scanner.stats()["paused_sweeps"] == 1
        assert scanner.report_payload()["summary"]["results"] == 0
    finally:
        batcher.shutdown()


def test_scanner_fault_aborts_then_resumes(env):
    """An armed ``audit.sweep`` fault aborts the sweep (error counted,
    unscanned keys re-marked dirty); the next sweep — fault cleared —
    judges the full corpus. The scanner never wedges."""
    batcher = MicroBatcher(env, max_batch_size=8, policy_timeout=10.0).start()
    scanner = make_scanner(env, batcher)
    try:
        scanner.snapshot.observe([pod_review("a"), pod_review("b")])
        with failpoints.active(
            "audit.sweep",
            lambda: (_ for _ in ()).throw(
                failpoints.FailpointError("injected sweep fault")
            ),
            count=1,
        ):
            with pytest.raises(failpoints.FailpointError):
                scanner.sweep(full=True)
        assert failpoints.fired_count("audit.sweep") == 1
        # fault cleared: the retry judges everything
        assert scanner.sweep(full=True) == 4
        assert scanner.report_payload()["summary"]["results"] == 4
    finally:
        batcher.shutdown()


def test_scanner_mid_sweep_batcher_shutdown_remarks_dirty(env):
    """A mid-sweep epoch retirement (the batcher shuts down under the
    scanner) aborts the sweep and re-marks unscanned keys dirty so the
    post-promote sweep picks them back up on the new epoch."""
    batcher = MicroBatcher(env, max_batch_size=8, policy_timeout=10.0)
    # not started, then shut down: submit_audit rejects like a retiring
    # epoch's batcher would
    batcher.shutdown()
    scanner = make_scanner(env, batcher, job_timeout_seconds=5.0)
    scanner.snapshot.observe([pod_review("a"), pod_review("b")])
    with pytest.raises(RuntimeError):
        scanner.sweep(full=True)
    # both resources back on the dirty set
    assert scanner.snapshot.stats()["dirty"] == 2
    # a healthy epoch finishes the job from the dirty set alone
    batcher2 = MicroBatcher(env, max_batch_size=8, policy_timeout=10.0).start()
    scanner.state.batcher = batcher2
    try:
        assert scanner.sweep(full=False) == 4
    finally:
        batcher2.shutdown()


# ---------------------------------------------------------------------------
# end to end: real server, HTTP surface, epoch coherence, audit-vs-validate
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def audit_server():
    import requests as _rq  # noqa: F401 — fail fast if missing

    from test_server import ServerHandle, make_config

    metrics_mod.reset_metrics_for_tests()
    policies = {
        "pod-privileged": parse_policy_entry(
            "pod-privileged", {"module": "builtin://pod-privileged"}
        ),
        # mutating policy, allowedToMutate UNSET (False) in protect mode:
        # the constraint FLIPS the verdict on /validate but must not on
        # /audit (reference handlers.rs:69-90)
        "caps-mutator": parse_policy_entry(
            "caps-mutator",
            {
                "module": "builtin://psp-capabilities",
                "settings": {
                    "allowed_capabilities": ["*"],
                    "required_drop_capabilities": ["NET_ADMIN"],
                },
            },
        ),
    }
    config = make_config(
        policies=policies,
        policy_timeout_seconds=5.0,
        audit_mode="interval",
        # cadence far beyond the test: sweeps are driven by hand or by
        # the lifecycle hooks, never by the timer
        audit_interval_seconds=60.0,
        audit_batch_size=8,
    )
    handle = ServerHandle(config)
    yield handle
    handle.stop()
    metrics_mod.reset_metrics_for_tests()


def _wait_until(predicate, timeout=15.0, step=0.05):
    deadline = time.perf_counter() + timeout
    while time.perf_counter() < deadline:
        if predicate():
            return True
        time.sleep(step)
    return False


def test_audit_skips_constraints_validate_applies_them(audit_server):
    """Satellite pin: the SAME mutating review through both endpoints —
    /validate (protect mode, not allowed to mutate) flips the verdict to
    a rejection with the patch stripped; /audit reports the RAW verdict,
    patch intact (service.rs:108-116, handlers.rs:69-90)."""
    import requests as rq

    from test_server import pod_review_body

    body = pod_review_body(False)
    r = rq.post(
        audit_server.url("/validate/caps-mutator"), json=body, timeout=30
    )
    assert r.status_code == 200
    resp = r.json()["response"]
    assert resp["allowed"] is False
    assert "patch" not in resp
    assert "not allow mutations" in resp["status"]["message"]

    r = rq.post(
        audit_server.url("/audit/caps-mutator"), json=body, timeout=30
    )
    assert r.status_code == 200
    resp = r.json()["response"]
    assert resp["allowed"] is True
    patch = json.loads(base64.b64decode(resp["patch"]))
    assert any(
        op["path"].endswith("/capabilities/drop")
        and op["value"] == ["NET_ADMIN"]
        for op in patch
    )
    assert resp["patchType"] == "JSONPatch"


def test_dirty_tracking_sweep_and_reports_endpoints(audit_server):
    import requests as rq

    from test_server import pod_review_body

    scanner = audit_server.server.state.audit
    assert scanner is not None
    # served /validate traffic lands in the snapshot (dirty-set tracker);
    # audit-origin traffic must NOT feed the snapshot
    doc = pod_review_body(True)
    doc["request"]["namespace"] = "ns-a"
    doc["request"]["object"]["metadata"]["namespace"] = "ns-a"
    r = rq.post(
        audit_server.url("/validate/pod-privileged"), json=doc, timeout=30
    )
    assert r.status_code == 200
    before = scanner.snapshot.stats()["resources"]
    r = rq.post(
        audit_server.url("/audit/pod-privileged"), json=doc, timeout=30
    )
    assert r.status_code == 200
    assert _wait_until(
        lambda: scanner.snapshot.stats()["resources"] == before
    )
    assert before >= 1

    scanner.sweep(full=True)
    r = rq.get(audit_server.url("/audit/reports"), timeout=10)
    assert r.status_code == 200
    body = r.json()
    assert body["summary"]["results"] >= 2  # >=1 resource x 2 policies
    assert body["scanner"]["full_sweeps"] >= 1
    assert body["scanner"]["freshness_seconds"] >= 0
    rows = {
        (x["namespace"], x["policy_id"]): x for x in body["reports"]
    }
    # the privileged pod in ns-a: denied by pod-privileged (raw verdict)
    assert rows[("ns-a", "pod-privileged")]["allowed"] is False
    assert rows[("ns-a", "caps-mutator")]["mutated"] is True

    # namespace-scoped listing filters
    r = rq.get(audit_server.url("/audit/reports/ns-a"), timeout=10)
    assert r.status_code == 200
    assert all(x["namespace"] == "ns-a" for x in r.json()["reports"])
    r = rq.get(audit_server.url("/audit/reports/no-such-ns"), timeout=10)
    assert r.json()["summary"]["results"] == 0
    # the readiness port serves the same listing (always the main
    # process — prefork workers only proxy the POST surface)
    r = rq.get(audit_server.readiness_url("/audit/reports"), timeout=10)
    assert r.status_code == 200
    assert r.json()["summary"]["results"] >= 2


def test_epoch_coherence_promote_rescans_rollback_stales(audit_server):
    """Acceptance: reports carry the epoch generation; a promote
    triggers a full re-scan stamped with the new epoch; a rollback marks
    the rolled-back epoch's reports stale and re-scans under the revived
    epoch."""
    import requests as rq

    from test_server import pod_review_body

    scanner = audit_server.server.state.audit
    lifecycle = audit_server.server.lifecycle
    assert lifecycle is not None
    # baseline: traffic + a by-hand full sweep stamped with epoch 0
    r = rq.post(
        audit_server.url("/validate/pod-privileged"),
        json=pod_review_body(False), timeout=30,
    )
    assert r.status_code == 200
    scanner.sweep(full=True)
    epoch0 = lifecycle.current_epoch
    body = scanner.report_payload()
    assert body["summary"]["results"] >= 2
    assert all(x["epoch"] == epoch0 for x in body["reports"])

    # PROMOTE: the post-promote hook queues a full re-scan on the
    # scanner thread; rows re-stamp with the new epoch
    sweeps_before = scanner.stats()["full_sweeps"]
    with lifecycle._swap_lock:
        current_policies = dict(lifecycle._current.policies)
    assert lifecycle.reload(policies=current_policies) == "promoted"
    epoch1 = lifecycle.current_epoch
    assert epoch1 == epoch0 + 1
    assert _wait_until(
        lambda: scanner.stats()["full_sweeps"] > sweeps_before
        and all(
            x["epoch"] == epoch1 for x in scanner.report_payload()["reports"]
        ),
        timeout=30,
    ), scanner.report_payload()["reports"]

    # ROLLBACK: hold the sweep lock so the stale marking (synchronous,
    # inside rollback()) is observable before the queued post-rollback
    # re-scan overwrites it
    with scanner._sweep_lock:
        assert lifecycle.rollback() == "rolled-back"
        assert lifecycle.current_epoch == epoch0
        body = scanner.report_payload()
        stale = [x for x in body["reports"] if x["stale"]]
        assert stale and all(x["epoch"] == epoch1 for x in stale)
        assert body["summary"]["stale"] == len(stale)
    # lock released: the queued post-rollback full sweep re-judges
    # everything under the revived epoch and clears the staleness
    assert _wait_until(
        lambda: all(
            x["epoch"] == epoch0 and not x["stale"]
            for x in scanner.report_payload()["reports"]
        ),
        timeout=30,
    ), scanner.report_payload()["reports"]


def test_reports_endpoint_404_when_audit_off():
    import requests as rq

    from test_server import ServerHandle, make_config

    config = make_config(
        policies={
            "pod-privileged": parse_policy_entry(
                "pod-privileged", {"module": "builtin://pod-privileged"}
            ),
        },
        policy_timeout_seconds=5.0,
        warmup_at_boot=False,
        policy_reload_mode="off",
    )
    handle = ServerHandle(config)
    try:
        assert handle.server.state.audit is None
        r = rq.get(handle.url("/audit/reports"), timeout=10)
        assert r.status_code == 404
        assert "audit scanner is disabled" in r.json()["message"]
    finally:
        handle.stop()


# ---------------------------------------------------------------------------
# review-hardening regressions: deferred full sweeps, report GC, lane cancel
# ---------------------------------------------------------------------------


def test_paused_full_sweep_keeps_its_pending_claim(env):
    """A full sweep skipped by the breaker pause (or failed outright)
    must stay pending — in on-promote mode nothing else would ever
    re-trigger it, and the new epoch would never re-judge the cluster."""

    class BreakerOpen:
        breaker_all_open = True

        def __getattr__(self, name):
            return getattr(env, name)

    batcher = MicroBatcher(env, max_batch_size=8).start()
    scanner = make_scanner(BreakerOpen(), batcher, mode="on-promote")
    try:
        with scanner._lock:
            scanner._full_pending = False  # as _loop does before sweeping
        assert scanner.sweep(full=True) == 0  # paused, not run
        with scanner._lock:
            assert scanner._full_pending is True  # claim restored
        # same for a faulted sweep
        with scanner._lock:
            scanner._full_pending = False
        with failpoints.active(
            "audit.sweep",
            lambda: (_ for _ in ()).throw(
                failpoints.FailpointError("injected")
            ),
            count=1,
        ):
            with pytest.raises(failpoints.FailpointError):
                scanner.sweep(full=True)
        with scanner._lock:
            assert scanner._full_pending is True
    finally:
        batcher.shutdown()


def test_reports_pruned_for_deleted_and_evicted_resources(env):
    """Report rows must not outlive their resource: a DELETE prunes on
    the next sweep, and a completed full sweep garbage-collects rows
    for resources/policies no longer in the inventory."""
    batcher = MicroBatcher(env, max_batch_size=8, policy_timeout=10.0).start()
    scanner = make_scanner(env, batcher)
    try:
        scanner.snapshot.observe([pod_review("a"), pod_review("b")])
        scanner.sweep(full=True)
        assert scanner.report_payload()["summary"]["resources"] == 2
        # DELETE of "a": its rows prune on the next (dirty) sweep
        scanner.snapshot.observe([pod_review("a", operation="DELETE")])
        scanner.sweep(full=False)
        body = scanner.report_payload()
        assert body["summary"]["resources"] == 1
        assert all(r["name"] == "b" for r in body["reports"])
        # stale policy rows GC on a full sweep: forge a row for a policy
        # the serving set does not carry
        scanner.reports.put([
            scanner.reports.row_from_result(
                "/v1/Pod/default/b", "removed-policy", pod_review("b"),
                AdmissionResponse(uid="u", allowed=True), epoch=0,
            )
        ])
        scanner.sweep(full=True)
        assert all(
            r["policy_id"] in ("priv", "happy")
            for r in scanner.report_payload()["reports"]
        )
    finally:
        batcher.shutdown()


def test_cancel_audit_removes_queued_job(env):
    batcher = MicroBatcher(env, max_batch_size=8)  # not started: job queues
    fut = batcher.submit_audit([("happy", pod_review("c"))])
    assert batcher.audit_lane_depth() == 1
    assert batcher.cancel_audit(fut) is True
    assert batcher.audit_lane_depth() == 0
    with pytest.raises(RuntimeError, match="cancelled"):
        fut.result(timeout=5)
    # cancelling an unknown/already-gone future is a no-op
    assert batcher.cancel_audit(fut) is False
    batcher.shutdown()


def test_sweep_job_timeout_cancels_lane_job_and_remarks_dirty(env):
    """The overload shape: the lane never gets an idle slot, the sweep
    times out — the stale job must leave the lane (no duplicate pileup)
    and the resources go back on the dirty set."""
    batcher = MicroBatcher(env, max_batch_size=8)  # loop not running:
    # submitted audit jobs never dispatch, like a saturated live lane
    scanner = make_scanner(env, batcher, job_timeout_seconds=0.3)
    scanner.snapshot.observe([pod_review("a")])
    with pytest.raises(RuntimeError, match="timed out"):
        scanner.sweep(full=True)
    assert batcher.audit_lane_depth() == 0  # cancelled, not lingering
    assert scanner.snapshot.stats()["dirty"] == 1
    with scanner._lock:
        assert scanner._full_pending is True
    batcher.shutdown()


def test_on_promote_mode_drains_deletions_between_sweeps(env):
    """on-promote mode may not sweep for days: the cadence loop must
    still drain observed DELETEs every tick — pruning the deleted
    objects' report rows and bounding the pending-deletion set."""
    batcher = MicroBatcher(env, max_batch_size=8, policy_timeout=10.0).start()
    scanner = make_scanner(env, batcher, mode="on-promote")
    try:
        scanner.snapshot.observe([pod_review("a"), pod_review("b")])
        scanner.sweep(full=True)
        assert scanner.report_payload()["summary"]["resources"] == 2
        with scanner._lock:
            scanner._full_pending = False  # no sweep will run
        scanner.start()
        scanner.snapshot.observe([pod_review("a", operation="DELETE")])
        assert _wait_until(
            lambda: scanner.report_payload()["summary"]["resources"] == 1
            and not scanner.snapshot.take_deletions()
        ), scanner.report_payload()["summary"]
        # no sweep ran: the prune happened on the cadence tick alone
        assert scanner.stats()["full_sweeps"] == 1
        assert scanner.stats()["dirty_sweeps"] == 0
    finally:
        scanner.shutdown()
        batcher.shutdown()


# ---------------------------------------------------------------------------
# Multi-tenant scoping (round 16, tenancy.py): the audit scanner serves
# the DEFAULT tenant only — a named tenant's validate traffic must never
# feed the default snapshot store (or its report rows).
# ---------------------------------------------------------------------------


def test_tenant_traffic_never_feeds_the_audit_snapshot(env):
    """server.py wires named-tenant batchers with audit_tracker=None:
    the snapshot store (and therefore every report row derived from it)
    stays scoped to objects admitted through the DEFAULT tenant."""
    store = SnapshotStore(max_bytes=10 * 1024 * 1024)
    default_batcher = MicroBatcher(
        env, max_batch_size=8, policy_timeout=10.0, audit_tracker=store,
    ).start()
    tenant_batcher = MicroBatcher(
        env, max_batch_size=8, policy_timeout=10.0, audit_tracker=None,
        tenant="ten-a",
    ).start()
    try:
        default_batcher.submit(
            "priv", pod_review("from-default"), RequestOrigin.VALIDATE
        ).result(timeout=30)
        tenant_batcher.submit(
            "priv", pod_review("from-tenant-a"), RequestOrigin.VALIDATE
        ).result(timeout=30)
        keys = [k for k, _ in store.collect()]
        assert any("from-default" in k for k in keys)
        assert not any("from-tenant-a" in k for k in keys)
        assert len(store) == 1
    finally:
        default_batcher.shutdown()
        tenant_batcher.shutdown()
