"""Config-layer tests, mirroring the reference's config parsing matrix
(src/config.rs:590-730 rstest cases)."""

import textwrap

import pytest
import yaml

from policy_server_tpu.config.cli import build_cli, generate_docs
from policy_server_tpu.config.config import Config, MeshSpec, TlsConfig, read_policies_file
from policy_server_tpu.config.sources import Sources
from policy_server_tpu.config.verification import VerificationConfig
from policy_server_tpu.models.policy import (
    Policy,
    PolicyGroup,
    PolicyMode,
    normalize_settings,
    parse_policies,
)

EXAMPLE_POLICIES = textwrap.dedent(
    """
    psp-apparmor:
      module: registry://ghcr.io/kubewarden/policies/psp-apparmor:v0.1.7
    psp-capabilities:
      module: registry://ghcr.io/kubewarden/policies/psp-capabilities:v0.1.7
      allowedToMutate: true
      settings:
        allowed_capabilities: ["*"]
        required_drop_capabilities: ["KILL"]
    pod-image-signatures:
      policies:
        sigstore_pgp:
          module: ghcr.io/kubewarden/policies/verify-image-signatures:v0.2.8
          settings:
            signatures:
              - image: "*"
                pubKeys: ["key1", "key2"]
        reject_latest_tag:
          module: ghcr.io/kubewarden/policies/trusted-repos-policy:v0.1.12
          settings:
            tags:
              reject:
                - latest
      expression: "sigstore_pgp() || reject_latest_tag()"
      message: "The group policy is rejected."
    """
)


def test_parse_policies_untagged_enum():
    policies = parse_policies(yaml.safe_load(EXAMPLE_POLICIES))
    assert set(policies) == {"psp-apparmor", "psp-capabilities", "pod-image-signatures"}
    apparmor = policies["psp-apparmor"]
    assert isinstance(apparmor, Policy)
    assert apparmor.policy_mode is PolicyMode.PROTECT
    assert apparmor.allowed_to_mutate is None
    caps = policies["psp-capabilities"]
    assert isinstance(caps, Policy)
    assert caps.allowed_to_mutate is True
    assert caps.settings == {
        "allowed_capabilities": ["*"],
        "required_drop_capabilities": ["KILL"],
    }
    group = policies["pod-image-signatures"]
    assert isinstance(group, PolicyGroup)
    assert set(group.policies) == {"sigstore_pgp", "reject_latest_tag"}
    assert group.expression == "sigstore_pgp() || reject_latest_tag()"
    assert group.message == "The group policy is rejected."


@pytest.mark.parametrize(
    "mode,expected",
    [(None, PolicyMode.PROTECT), ("monitor", PolicyMode.MONITOR), ("protect", PolicyMode.PROTECT)],
)
def test_policy_mode_parse(mode, expected):
    assert PolicyMode.parse(mode) is expected


def test_policy_mode_invalid():
    with pytest.raises(ValueError):
        PolicyMode.parse("enforce")


def test_policy_name_with_slash_rejected():
    # config.rs:237-258
    with pytest.raises(ValueError, match="must not contain '/'"):
        parse_policies({"bad/name": {"module": "file:///x.wasm"}})


def test_unknown_policy_field_rejected():
    with pytest.raises(ValueError, match="unknown policy fields"):
        parse_policies({"p": {"module": "file:///x.wasm", "bogus": 1}})


def test_group_requires_expression_and_message():
    with pytest.raises(ValueError, match="expression"):
        parse_policies(
            {"g": {"policies": {"a": {"module": "file:///x.wasm"}}, "message": "m"}}
        )


def test_settings_yaml_to_json_normalization():
    # config.rs:306-328: YAML-only scalars become JSON-safe
    import datetime

    raw = {"when": datetime.date(2020, 1, 1), "nested": {"xs": (1, 2)}}
    assert normalize_settings(raw) == {"when": "2020-01-01", "nested": {"xs": [1, 2]}}


def test_sources_parsing():
    doc = yaml.safe_load(
        textwrap.dedent(
            """
            insecure_sources: ["registry.dev.example.com"]
            source_authorities:
              "registry.pre.example.com":
                - type: Data
                  data: "PEM"
            """
        )
    )
    sources = Sources.from_dict(doc)
    assert sources.is_insecure("registry.dev.example.com")
    assert not sources.is_insecure("other")
    assert sources.authorities_for("registry.pre.example.com")[0].data == "PEM"


def test_verification_config():
    doc = yaml.safe_load(
        textwrap.dedent(
            """
            apiVersion: v1
            allOf:
              - kind: githubAction
                owner: kubewarden
            anyOf:
              minimumMatches: 2
              signatures:
                - kind: pubKey
                  key: k1
                - kind: pubKey
                  key: k2
                - kind: genericIssuer
                  issuer: https://example.com
                  subject:
                    urlPrefix: https://github.com/kubewarden
            """
        )
    )
    cfg = VerificationConfig.from_dict(doc)
    assert cfg.all_of[0].kind == "githubAction"
    assert cfg.any_of.minimum_matches == 2
    # urlPrefix gets '/' appended (verification.yml.example note)
    assert cfg.any_of.signatures[2].subject.url_prefix.endswith("kubewarden/")


def test_verification_bad_api_version():
    with pytest.raises(ValueError, match="apiVersion"):
        VerificationConfig.from_dict({"apiVersion": "v2", "allOf": []})


def test_tls_config_validation():
    TlsConfig().validate()
    TlsConfig(cert_file="c", key_file="k").validate()
    with pytest.raises(ValueError):
        TlsConfig(cert_file="c").validate()
    with pytest.raises(ValueError):
        TlsConfig(client_ca_file=("ca",)).validate()


@pytest.mark.parametrize(
    "spec,axes",
    [
        ("auto", (("data", 0),)),
        ("data:8", (("data", 8),)),
        ("data:4,policy:2", (("data", 4), ("policy", 2))),
    ],
)
def test_mesh_spec(spec, axes):
    assert MeshSpec.parse(spec).axes == axes


@pytest.mark.parametrize("spec", ["bogus:2", "data:x", "data:0", "data:2,data:2"])
def test_mesh_spec_invalid(spec):
    with pytest.raises(ValueError):
        MeshSpec.parse(spec)


def test_config_from_args(tmp_path):
    policies = tmp_path / "policies.yml"
    policies.write_text(EXAMPLE_POLICIES)
    parser = build_cli()
    args = parser.parse_args(["--policies", str(policies), "--workers", "4"])
    cfg = Config.from_args(args)
    assert cfg.pool_size == 4
    assert cfg.port == 3000
    assert cfg.readiness_probe_port == 8081
    assert set(cfg.policies) == {"psp-apparmor", "psp-capabilities", "pod-image-signatures"}
    assert cfg.policy_timeout == 2.0
    assert cfg.evaluation_backend == "jax"


def test_config_env_fallback(tmp_path, monkeypatch):
    # cli.rs: every flag has a KUBEWARDEN_* env fallback
    policies = tmp_path / "policies.yml"
    policies.write_text("{}")
    monkeypatch.setenv("KUBEWARDEN_PORT", "3001")
    monkeypatch.setenv("KUBEWARDEN_POLICIES", str(policies))
    parser = build_cli()
    args = parser.parse_args([])
    cfg = Config.from_args(args)
    assert cfg.port == 3001
    assert cfg.policies == {}


def test_timeout_protection_disable(tmp_path):
    policies = tmp_path / "policies.yml"
    policies.write_text("{}")
    parser = build_cli()
    args = parser.parse_args(
        ["--policies", str(policies), "--disable-timeout-protection"]
    )
    cfg = Config.from_args(args)
    assert cfg.policy_timeout is None


def test_generate_docs_mentions_all_flags():
    docs = generate_docs()
    for flag in ["--addr", "--policies", "--policy-timeout", "--evaluation-backend", "--mesh"]:
        assert flag in docs


def test_admission_review_roundtrip(admission_review_request):
    req = admission_review_request.request
    assert req.uid == "hello"
    assert req.kind.kind == "Scale"
    assert req.operation == "UPDATE"
    d = req.to_dict()
    assert d["userInfo"]["username"] == "admin"
    assert "oldObject" not in d  # None fields dropped


def test_admission_response_reject():
    from policy_server_tpu.models import AdmissionResponse

    resp = AdmissionResponse.reject("uid1", "nope", 403)
    d = resp.to_dict()
    assert d == {
        "uid": "uid1",
        "allowed": False,
        "status": {"message": "nope", "code": 403},
    }


def test_validate_request_uid():
    from policy_server_tpu.models import ValidateRequest

    raw = ValidateRequest.from_raw({"uid": "r1", "x": 1})
    assert raw.uid() == "r1"
    assert ValidateRequest.from_raw([1, 2]).uid() == ""
