"""Context-aware policy tests: snapshot service semantics, per-policy
capability allowlists (EvaluationContext parity), jax-vs-oracle agreement
with injected context, snapshot refresh, and the fail-closed empty-cluster
behavior."""

from __future__ import annotations

import pytest

from policy_server_tpu.context import (
    ContextSnapshotService,
    StaticContextFetcher,
)
from policy_server_tpu.evaluation.environment import EvaluationEnvironmentBuilder
from policy_server_tpu.models import AdmissionReviewRequest, ValidateRequest
from policy_server_tpu.models.policy import parse_policy_entry

from conftest import build_admission_review_dict

NS_ALLOWLIST = [{"apiVersion": "v1", "kind": "Namespace"}]


def ns_object(name: str) -> dict:
    return {"metadata": {"name": name}}


def make_service(namespaces: list[str]) -> ContextSnapshotService:
    fetcher = StaticContextFetcher(
        {"v1/Namespace": [ns_object(n) for n in namespaces]}
    )
    from policy_server_tpu.models.policy import ContextAwareResource

    service = ContextSnapshotService(
        fetcher,
        wanted=[ContextAwareResource("v1", "Namespace")],
    )
    service.refresh()
    return service


def request_in(namespace: str) -> ValidateRequest:
    doc = build_admission_review_dict()
    doc["request"]["namespace"] = namespace
    return ValidateRequest.from_admission(
        AdmissionReviewRequest.from_dict(doc).request
    )


def build_env(backend: str, service, with_allowlist: bool = True):
    entry = {
        "module": "builtin://namespace-exists",
        **({"contextAwareResources": NS_ALLOWLIST} if with_allowlist else {}),
    }
    return EvaluationEnvironmentBuilder(
        backend=backend, context_service=service
    ).build({"ns-exists": parse_policy_entry("ns-exists", entry)})


@pytest.mark.parametrize("backend", ["jax", "oracle"])
def test_namespace_exists_against_snapshot(backend):
    service = make_service(["default", "prod"])
    env = build_env(backend, service)
    assert env.validate("ns-exists", request_in("prod")).allowed
    resp = env.validate("ns-exists", request_in("ghost"))
    assert not resp.allowed
    assert "ghost" in resp.status.message


def test_jax_matches_oracle_with_context():
    service = make_service(["a", "b", "team-x"])
    jax_env = build_env("jax", service)
    oracle_env = build_env("oracle", service)
    for ns in ("a", "b", "team-x", "nope", "A"):
        r1 = jax_env.validate("ns-exists", request_in(ns))
        r2 = oracle_env.validate("ns-exists", request_in(ns))
        assert r1.to_dict() == r2.to_dict(), ns


def test_without_allowlist_policy_sees_empty_cluster():
    """Capability enforcement: no contextAwareResources declaration → the
    snapshot slice is empty → fail-closed."""
    service = make_service(["default"])
    env = build_env("jax", service, with_allowlist=False)
    assert not env.validate("ns-exists", request_in("default")).allowed


def test_snapshot_refresh_changes_verdicts():
    fetcher = StaticContextFetcher({"v1/Namespace": [ns_object("old")]})
    from policy_server_tpu.models.policy import ContextAwareResource

    service = ContextSnapshotService(
        fetcher, wanted=[ContextAwareResource("v1", "Namespace")]
    )
    service.refresh()
    env = build_env("jax", service)
    assert not env.validate("ns-exists", request_in("new")).allowed
    fetcher.resources["v1/Namespace"] = [ns_object("old"), ns_object("new")]
    service.refresh()
    assert env.validate("ns-exists", request_in("new")).allowed
    assert service.snapshot().version == 2


def test_batched_context_evaluation():
    service = make_service(["default", "prod"])
    env = build_env("jax", service)
    items = [
        ("ns-exists", request_in("default")),
        ("ns-exists", request_in("ghost")),
        ("ns-exists", request_in("prod")),
    ]
    results = env.validate_batch(items)
    assert [r.allowed for r in results] == [True, False, True]


# -- watch-based freshness (staleness contract, context/service.py) ---------


class FakeWatchFetcher:
    """list+watch double: LIST serves ``self.items``; watch() yields events
    pushed through a queue (None = close the stream cleanly)."""

    def __init__(self, items: list[dict]):
        import queue as _q

        self.items = list(items)
        self.events: "_q.Queue" = _q.Queue()
        self.lists = 0
        self.watches = 0
        self.watch_versions: list[str] = []

    # poll-mode API (boot prefetch uses it)
    def fetch(self, wanted):
        from policy_server_tpu.context.service import resource_key

        return {resource_key(r): tuple(self.items) for r in wanted}

    def list_with_version(self, resource):
        self.lists += 1
        return tuple(self.items), f"rv-{self.lists}"

    def watch(self, resource, resource_version):
        self.watches += 1
        self.watch_versions.append(resource_version)
        while True:
            ev = self.events.get(timeout=10)
            if ev is None:  # clean server-side stream close
                return
            if isinstance(ev, Exception):
                raise ev
            yield ev


def wait_for(predicate, timeout=5.0):
    import time

    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(0.01)
    return False


def watch_event(etype: str, name: str, rv: str = "1") -> dict:
    # name-only metadata, matching ns_object() fixtures: identity falls
    # back to (namespace, name) when uid is absent (_object_key)
    return {
        "type": etype,
        "object": {"metadata": {"name": name, "resourceVersion": rv}},
    }


@pytest.fixture()
def watch_service():
    from policy_server_tpu.models.policy import ContextAwareResource

    fetcher = FakeWatchFetcher([ns_object("seed")])
    # refresh_seconds=0.5: periodic resync (10x = 5s) stays outside the
    # test window so LIST counts are deterministic
    service = ContextSnapshotService(
        fetcher,
        wanted=[ContextAwareResource("v1", "Namespace")],
        refresh_seconds=0.5,
    ).start()
    yield fetcher, service
    service._stop.set()  # noqa: SLF001 — stop BEFORE waking the watcher so
    fetcher.events.put(None)  # it exits instead of re-listing
    service.stop()


def names(service) -> set:
    return {
        (o.get("metadata") or {}).get("name")
        for o in service.snapshot().resources.get("v1/Namespace", ())
    }


def test_watch_mode_applies_events(watch_service):
    """ADDED/MODIFIED/DELETED events update the snapshot without re-LIST:
    freshness = event latency, not the refresh period."""
    fetcher, service = watch_service
    assert service.watch_enabled
    assert wait_for(lambda: fetcher.watches == 1)
    baseline_lists = fetcher.lists

    fetcher.events.put(watch_event("ADDED", "fresh"))
    assert wait_for(lambda: "fresh" in names(service))
    fetcher.events.put(watch_event("DELETED", "seed"))
    assert wait_for(lambda: "seed" not in names(service))
    assert fetcher.lists == baseline_lists  # no re-list needed
    assert service.snapshot().version >= 3


def test_watch_error_event_triggers_relist(watch_service):
    """A 410-Gone-style ERROR event falls back to a fresh LIST and resumes
    watching from the new resourceVersion."""
    fetcher, service = watch_service
    assert wait_for(lambda: fetcher.watches == 1)
    fetcher.items.append(ns_object("recovered"))
    fetcher.events.put({"type": "ERROR", "object": {"code": 410}})
    assert wait_for(lambda: fetcher.watches == 2)
    assert wait_for(lambda: "recovered" in names(service))
    assert fetcher.watch_versions == ["rv-1", "rv-2"]


def test_watch_transport_error_backs_off_and_recovers(watch_service):
    """A transport failure keeps the last good snapshot serving and
    re-establishes list+watch after the backoff."""
    fetcher, service = watch_service
    assert wait_for(lambda: fetcher.watches == 1)
    assert "seed" in names(service)  # last good stays visible
    fetcher.items.append(ns_object("after-crash"))
    fetcher.events.put(ConnectionError("stream reset"))
    assert wait_for(lambda: fetcher.watches == 2)
    assert wait_for(lambda: "after-crash" in names(service))
    assert "seed" in names(service)


def test_watch_resync_relists_after_interval():
    """The periodic resync safety net: a watch event silently dropped by
    the stream is repaired by the next post-interval re-LIST."""
    from policy_server_tpu.models.policy import ContextAwareResource

    fetcher = FakeWatchFetcher([ns_object("a")])
    service = ContextSnapshotService(
        fetcher,
        wanted=[ContextAwareResource("v1", "Namespace")],
        refresh_seconds=0.01,
    )
    service.RESYNC_MULTIPLIER = 1  # resync due 10ms after the boot LIST
    service.start()
    try:
        assert wait_for(lambda: fetcher.watches == 1)
        # an object appears but its watch event is "lost" (never pushed)
        fetcher.items.append(ns_object("missed"))
        import time as _time

        _time.sleep(0.05)  # let the resync interval elapse
        fetcher.events.put(None)  # stream close → resync due → re-LIST
        assert wait_for(lambda: "missed" in names(service))
        assert fetcher.lists >= 2
    finally:
        service._stop.set()  # noqa: SLF001
        fetcher.events.put(None)
        service.stop()


def test_boot_list_http_error_serves_empty_view_for_that_kind():
    """RBAC denying list on one kind (HTTP 403) must not crash boot: the
    kind serves an empty view and its watcher keeps retrying."""
    import requests as _requests

    from policy_server_tpu.models.policy import ContextAwareResource

    class DeniedFetcher(FakeWatchFetcher):
        def list_with_version(self, resource):
            self.lists += 1
            resp = _requests.Response()
            resp.status_code = 403
            raise _requests.HTTPError("403 Forbidden", response=resp)

    fetcher = DeniedFetcher([ns_object("hidden")])
    service = ContextSnapshotService(
        fetcher,
        wanted=[ContextAwareResource("v1", "Namespace")],
        refresh_seconds=0.5,
    ).start()  # must not raise
    try:
        assert service.snapshot().resources.get("v1/Namespace") == ()
    finally:
        service._stop.set()  # noqa: SLF001
        fetcher.events.put(None)
        service.stop()


def test_poll_mode_when_watch_disabled():
    """--context-no-watch forces periodic LIST refresh."""
    from policy_server_tpu.models.policy import ContextAwareResource

    fetcher = FakeWatchFetcher([ns_object("a")])
    service = ContextSnapshotService(
        fetcher,
        wanted=[ContextAwareResource("v1", "Namespace")],
        refresh_seconds=0.05,
        watch=False,
    ).start()
    try:
        assert not service.watch_enabled
        fetcher.items.append(ns_object("b"))
        assert wait_for(lambda: "b" in names(service))
        assert fetcher.watches == 0
    finally:
        service.stop()


# -- kube client TLS semantics ----------------------------------------------


def test_kube_client_never_silently_skips_tls(monkeypatch, tmp_path):
    """Without a cluster CA the kube client must use the system trust store
    (verify=True) — never verify=False unless explicitly opted in
    (round-1 VERDICT weak #7)."""
    from policy_server_tpu.context.service import KubeApiFetcher

    captured: list = []

    class _Resp:
        status_code = 200

        def json(self):
            return {}

    def fake_get(url, headers=None, verify=None, timeout=None, **kwargs):
        captured.append(verify)
        return _Resp()

    monkeypatch.setattr(
        "policy_server_tpu.context.service.requests.get", fake_get
    )

    KubeApiFetcher(api_server="https://kube.example", token="t")
    assert captured[-1] is True  # system trust store, not False

    ca = tmp_path / "ca.crt"
    ca.write_text("dummy")
    KubeApiFetcher(api_server="https://kube.example", token="t", ca_file=str(ca))
    assert captured[-1] == str(ca)

    KubeApiFetcher(
        api_server="https://kube.example", token="t",
        insecure_skip_tls_verify=True,
    )
    assert captured[-1] is False  # explicit opt-in only
