"""Context-aware policy tests: snapshot service semantics, per-policy
capability allowlists (EvaluationContext parity), jax-vs-oracle agreement
with injected context, snapshot refresh, and the fail-closed empty-cluster
behavior."""

from __future__ import annotations

import pytest

from policy_server_tpu.context import (
    ContextSnapshotService,
    StaticContextFetcher,
)
from policy_server_tpu.evaluation.environment import EvaluationEnvironmentBuilder
from policy_server_tpu.models import AdmissionReviewRequest, ValidateRequest
from policy_server_tpu.models.policy import parse_policy_entry

from conftest import build_admission_review_dict

NS_ALLOWLIST = [{"apiVersion": "v1", "kind": "Namespace"}]


def ns_object(name: str) -> dict:
    return {"metadata": {"name": name}}


def make_service(namespaces: list[str]) -> ContextSnapshotService:
    fetcher = StaticContextFetcher(
        {"v1/Namespace": [ns_object(n) for n in namespaces]}
    )
    from policy_server_tpu.models.policy import ContextAwareResource

    service = ContextSnapshotService(
        fetcher,
        wanted=[ContextAwareResource("v1", "Namespace")],
    )
    service.refresh()
    return service


def request_in(namespace: str) -> ValidateRequest:
    doc = build_admission_review_dict()
    doc["request"]["namespace"] = namespace
    return ValidateRequest.from_admission(
        AdmissionReviewRequest.from_dict(doc).request
    )


def build_env(backend: str, service, with_allowlist: bool = True):
    entry = {
        "module": "builtin://namespace-exists",
        **({"contextAwareResources": NS_ALLOWLIST} if with_allowlist else {}),
    }
    return EvaluationEnvironmentBuilder(
        backend=backend, context_service=service
    ).build({"ns-exists": parse_policy_entry("ns-exists", entry)})


@pytest.mark.parametrize("backend", ["jax", "oracle"])
def test_namespace_exists_against_snapshot(backend):
    service = make_service(["default", "prod"])
    env = build_env(backend, service)
    assert env.validate("ns-exists", request_in("prod")).allowed
    resp = env.validate("ns-exists", request_in("ghost"))
    assert not resp.allowed
    assert "ghost" in resp.status.message


def test_jax_matches_oracle_with_context():
    service = make_service(["a", "b", "team-x"])
    jax_env = build_env("jax", service)
    oracle_env = build_env("oracle", service)
    for ns in ("a", "b", "team-x", "nope", "A"):
        r1 = jax_env.validate("ns-exists", request_in(ns))
        r2 = oracle_env.validate("ns-exists", request_in(ns))
        assert r1.to_dict() == r2.to_dict(), ns


def test_without_allowlist_policy_sees_empty_cluster():
    """Capability enforcement: no contextAwareResources declaration → the
    snapshot slice is empty → fail-closed."""
    service = make_service(["default"])
    env = build_env("jax", service, with_allowlist=False)
    assert not env.validate("ns-exists", request_in("default")).allowed


def test_snapshot_refresh_changes_verdicts():
    fetcher = StaticContextFetcher({"v1/Namespace": [ns_object("old")]})
    from policy_server_tpu.models.policy import ContextAwareResource

    service = ContextSnapshotService(
        fetcher, wanted=[ContextAwareResource("v1", "Namespace")]
    )
    service.refresh()
    env = build_env("jax", service)
    assert not env.validate("ns-exists", request_in("new")).allowed
    fetcher.resources["v1/Namespace"] = [ns_object("old"), ns_object("new")]
    service.refresh()
    assert env.validate("ns-exists", request_in("new")).allowed
    assert service.snapshot().version == 2


def test_batched_context_evaluation():
    service = make_service(["default", "prod"])
    env = build_env("jax", service)
    items = [
        ("ns-exists", request_in("default")),
        ("ns-exists", request_in("ghost")),
        ("ns-exists", request_in("prod")),
    ]
    results = env.validate_batch(items)
    assert [r.allowed for r in results] == [True, False, True]


# -- kube client TLS semantics ----------------------------------------------


def test_kube_client_never_silently_skips_tls(monkeypatch, tmp_path):
    """Without a cluster CA the kube client must use the system trust store
    (verify=True) — never verify=False unless explicitly opted in
    (round-1 VERDICT weak #7)."""
    from policy_server_tpu.context.service import KubeApiFetcher

    captured: list = []

    class _Resp:
        status_code = 200

        def json(self):
            return {}

    def fake_get(url, headers=None, verify=None, timeout=None):
        captured.append(verify)
        return _Resp()

    monkeypatch.setattr(
        "policy_server_tpu.context.service.requests.get", fake_get
    )

    KubeApiFetcher(api_server="https://kube.example", token="t")
    assert captured[-1] is True  # system trust store, not False

    ca = tmp_path / "ca.crt"
    ca.write_text("dummy")
    KubeApiFetcher(api_server="https://kube.example", token="t", ca_file=str(ca))
    assert captured[-1] == str(ca)

    KubeApiFetcher(
        api_server="https://kube.example", token="t",
        insecure_skip_tls_verify=True,
    )
    assert captured[-1] is False  # explicit opt-in only
