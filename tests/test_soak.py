"""Soak-engine unit tests (tools/soak): scenario determinism, SLO
classification + gate logic, fault-storm scheduling, artifact shape.
The full stack soak itself runs as ``make soak-smoke`` (CI-gated) and a
slow-marked mini-engine case here.
"""

from __future__ import annotations

import json
import random

import pytest

from tools.soak import scenarios
from tools.soak.faults import FaultStorm
from tools.soak.slo import SLORecorder, write_artifact

# ---------------------------------------------------------------------------
# scenarios
# ---------------------------------------------------------------------------


def test_trace_is_seed_deterministic():
    a = scenarios.build_trace(1234, 600)
    b = scenarios.build_trace(1234, 600)
    assert [(i.path, i.body, i.expect) for i in a.items] == [
        (i.path, i.body, i.expect) for i in b.items
    ]
    assert [(w.kind, w.conns, w.param) for w in a.abuse] == [
        (w.kind, w.conns, w.param) for w in b.abuse
    ]
    c = scenarios.build_trace(99, 600)
    assert [i.body for i in a.items] != [i.body for i in c.items]


def test_trace_covers_every_scenario_family():
    trace = scenarios.build_trace(42, 2000)
    families = {i.scenario for i in trace.items}
    assert families >= {
        "rollout_storm", "namespace_churn", "schema_diversity",
        "mutating_chain", "adversarial_payloads", "unknown_policy",
    }
    kinds = {w.kind for w in trace.abuse}
    assert kinds == {"slowloris", "malformed_flood", "midbody_disconnect"}
    # expectation classes present: ok, 422 (malformed), 404 (unknown)
    assert {i.expect for i in trace.items} == {"ok", "422", "404"}


def test_trace_bodies_are_wire_ready():
    trace = scenarios.build_trace(7, 400)
    for item in trace.items:
        assert item.path.startswith(("/validate/", "/validate_raw/"))
        assert isinstance(item.body, bytes) and item.body
        if item.expect == "ok" and item.scenario != "adversarial_payloads":
            json.loads(item.body)  # well-formed unless adversarial


# ---------------------------------------------------------------------------
# SLO recorder + gate
# ---------------------------------------------------------------------------


def test_classification_matrix():
    rec = SLORecorder(window_seconds=60.0)
    assert rec.classify(200, "ok") == "ok"
    assert rec.classify(422, "422") == "ok"
    assert rec.classify(404, "404") == "ok"
    assert rec.classify(429, "ok") == "shed"
    assert rec.classify(504, "ok") == "expired"
    assert rec.classify(422, "ok") == "unexplained"
    assert rec.classify(500, "ok") == "unexplained"
    # inside a declared fault window, 5xx become fault_injected — 4xx
    # mismatches stay unexplained
    rec.note_fault_window("frontend_fault", duration=60.0)
    assert rec.classify(500, "ok") == "fault_injected"
    assert rec.classify(599, "ok") == "fault_injected"  # conn-drop sentinel
    assert rec.classify(422, "ok") == "unexplained"


def test_policy_churn_storm_is_seeded_and_keeps_base_policies():
    """Round 15: every rewrite preserves the base policy ids (the
    flowing trace must not start 404ing) and varies the churn-tenant
    block; the schedule is deterministic per seed and respects the
    >=3 s spacing the 1 s digest poll needs."""
    base = "pod-privileged:\n  module: builtin://pod-privileged\n"
    a = scenarios.policy_churn_storm(
        random.Random(7), 60.0, base, rewrites=4
    )
    b = scenarios.policy_churn_storm(
        random.Random(7), 60.0, base, rewrites=4
    )
    assert [(r.at, r.yaml_text) for r in a] == [
        (r.at, r.yaml_text) for r in b
    ]
    c = scenarios.policy_churn_storm(
        random.Random(8), 60.0, base, rewrites=4
    )
    assert [r.yaml_text for r in a] != [r.yaml_text for r in c]
    assert len(a) == 4
    for i, rw in enumerate(a):
        assert "pod-privileged:" in rw.yaml_text  # base survives
        assert rw.marker == f"churn-r{i}-t0-fence"
        assert f"{rw.marker}:" in rw.yaml_text
        assert 0.1 * 60 <= rw.at <= 0.95 * 60
    # markers are unique per rewrite: a landed marker identifies WHICH
    # rewrite's reload is serving
    assert len({rw.marker for rw in a}) == 4
    for prev, nxt in zip(a, a[1:]):
        assert nxt.at - prev.at >= 2.0
    # the rewritten sets PARSE into real policies (a rewrite that the
    # candidate compile rejects every time tests only the rollback path)
    import yaml

    from policy_server_tpu.models.policy import parse_policy_entry

    for rw in a:
        doc = yaml.safe_load(rw.yaml_text)
        parsed = {k: parse_policy_entry(k, v) for k, v in doc.items()}
        assert "pod-privileged" in parsed and len(parsed) > 1
    assert scenarios.policy_churn_storm(
        random.Random(7), 60.0, base, rewrites=0
    ) == []


def test_gate_policy_churn_check():
    """policy_rewrites dict: all-applied AND landed passes; a missed
    rewrite fails; writes without a landed reload fail (a storm whose
    every reload rolled back proves nothing); None omits the check."""
    from tools.soak.faults import FaultEvent

    rec = SLORecorder(window_seconds=0.05)
    rec.record(200, 5.0, "ok")
    rec.finish()
    rec.record_abuse({"kind": "malformed_flood", "passed": True})
    applied = [
        FaultEvent(at=1.0, kind=k, applied_at=1.0)
        for k in ("sighup", "device_fault", "watch_fault")
    ]
    gate = rec.gate(
        p99_budget_ms=100.0, fault_events=applied,
        policy_rewrites={"applied": 2, "planned": 2, "landed": True},
    )
    assert gate["passed"], gate["checks"]
    assert gate["checks"]["policy_churn_happened"]
    gate2 = rec.gate(
        p99_budget_ms=100.0, fault_events=applied,
        policy_rewrites={"applied": 1, "planned": 2, "landed": True},
    )
    assert not gate2["passed"]
    assert not gate2["checks"]["policy_churn_happened"]
    gate3 = rec.gate(
        p99_budget_ms=100.0, fault_events=applied,
        policy_rewrites={"applied": 2, "planned": 2, "landed": False},
    )
    assert not gate3["checks"]["policy_churn_happened"]
    gate4 = rec.gate(p99_budget_ms=100.0, fault_events=applied)
    assert "policy_churn_happened" not in gate4["checks"]


def test_gate_requires_storm_and_clean_traffic():
    from tools.soak.faults import FaultEvent

    rec = SLORecorder(window_seconds=0.05)
    for _ in range(50):
        rec.record(200, 5.0, "ok")
    rec.record(429, 0.0, "ok")
    rec.finish()
    applied = [
        FaultEvent(at=1.0, kind=k, applied_at=1.0)
        for k in ("sighup", "device_fault", "watch_fault")
    ]
    rec.record_abuse({"kind": "malformed_flood", "passed": True})
    gate = rec.gate(p99_budget_ms=100.0, fault_events=applied)
    assert gate["passed"], gate["checks"]
    assert gate["totals"]["shed"] == 1

    # one unexplained response fails the gate
    rec2 = SLORecorder(window_seconds=0.05)
    rec2.record(200, 5.0, "ok")
    rec2.record(500, 5.0, "ok")
    rec2.finish()
    rec2.record_abuse({"kind": "malformed_flood", "passed": True})
    gate2 = rec2.gate(p99_budget_ms=100.0, fault_events=applied)
    assert not gate2["passed"]
    assert not gate2["checks"]["zero_unexplained_non_2xx"]
    assert gate2["totals"]["unexplained_samples"]

    # an un-applied storm fails the gate even with clean traffic
    rec3 = SLORecorder(window_seconds=0.05)
    rec3.record(200, 5.0, "ok")
    rec3.finish()
    rec3.record_abuse({"kind": "malformed_flood", "passed": True})
    gate3 = rec3.gate(
        p99_budget_ms=100.0,
        fault_events=[FaultEvent(at=1.0, kind="sighup")],  # never applied
    )
    assert not gate3["passed"]
    assert not gate3["checks"]["fault_storm_happened"]

    # a soak where every reload rolled back fails the promoted-flip
    # check; one promotion passes it; None (no lifecycle) omits it
    gate4 = rec.gate(
        p99_budget_ms=100.0, fault_events=applied, promoted_reloads=0
    )
    assert not gate4["passed"]
    assert not gate4["checks"]["epoch_flip_promoted"]
    gate5 = rec.gate(
        p99_budget_ms=100.0, fault_events=applied, promoted_reloads=1
    )
    assert gate5["passed"], gate5["checks"]
    assert "epoch_flip_promoted" not in gate["checks"]


def test_windows_roll_and_publish_soak_state():
    class FakeState:
        soak = None

    state = FakeState()
    rec = SLORecorder(window_seconds=0.01, soak_state=state)
    rec.record(200, 4.0, "ok")
    import time

    time.sleep(0.03)
    rec.record(200, 6.0, "ok")
    rec.finish()
    assert len(rec.windows()) >= 1
    assert state.soak is None  # finish() clears the live gauge source


# ---------------------------------------------------------------------------
# fault storm scheduling
# ---------------------------------------------------------------------------


def test_storm_schedule_is_seeded_and_bounded():
    class FakeServer:
        class config:
            breaker_failure_threshold = 5

    a = FaultStorm.schedule(random.Random(5), 60.0, FakeServer())
    b = FaultStorm.schedule(random.Random(5), 60.0, FakeServer())
    assert [(e.at, e.kind) for e in a.events] == [
        (e.at, e.kind) for e in b.events
    ]
    kinds = [e.kind for e in a.events]
    assert kinds.count("sighup") == 2  # mid-storm + late reload
    for core in ("device_fault", "watch_fault", "audit_fault",
                 "frontend_fault", "reload_poison", "stream_close"):
        assert core in kinds
    assert "worker_kill" not in kinds  # workers=False
    for e in a.events:
        assert 0.05 * 60 <= e.at <= 0.95 * 60
    assert [e.at for e in a.events] == sorted(e.at for e in a.events)
    # the device-fault window must CLOSE before the late reload so the
    # promoted-flip gate check is deterministic (lingering device arms
    # poisoned every reload in the first soak runs); the poisoned
    # reload goes early so its reload.compile arm is consumed by its
    # own reload, never the late flip
    late = max(e.at for e in a.events if e.kind == "sighup")
    device = next(e for e in a.events if e.kind == "device_fault")
    poison = next(e for e in a.events if e.kind == "reload_poison")
    assert device.at + a.window_seconds < late
    assert poison.at <= 0.25 * 60
    assert 2.0 <= a.window_seconds <= 5.0


def test_device_fault_window_auto_disarms():
    """An armed device fault the live path never consumed (cache hits,
    host fast-path) must not outlive its window — lingering arms
    poisoned later epochs' warmup dispatches in the first soak runs."""
    import time

    from policy_server_tpu import failpoints

    class FakeServer:
        class config:
            breaker_failure_threshold = 1

    storm = FaultStorm(server=FakeServer(), window_seconds=0.2)
    try:
        effect = storm._device_fault()
        assert "auto-disarm" in effect
        with pytest.raises(Exception, match="soak-device-fault"):
            failpoints.fire("device.fetch")  # one arm consumed live
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            try:
                failpoints.fire("device.fetch")
            except Exception:
                time.sleep(0.05)  # window not closed yet
            else:
                break  # disarmed: fire is a no-op again
        else:
            raise AssertionError("device.fetch never auto-disarmed")
    finally:
        storm.stop()


def test_storm_includes_worker_kill_only_with_workers():
    class FakeServer:
        class config:
            breaker_failure_threshold = 5

    storm = FaultStorm.schedule(
        random.Random(1), 60.0, FakeServer(), workers=True
    )
    assert "worker_kill" in [e.kind for e in storm.events]


# ---------------------------------------------------------------------------
# artifact
# ---------------------------------------------------------------------------


def test_artifact_shape(tmp_path):
    path = tmp_path / "BENCH_soak_test.json"
    write_artifact(
        str(path),
        meta={"seed": 1, "preset": "unit"},
        windows=[{"t": 0, "rps": 10.0}],
        faults=[{"at": 1.0, "kind": "sighup", "applied_at": 1.1}],
        gate={"passed": True, "checks": {}},
        extra={"watch_feed": {"events_applied": 3}},
    )
    doc = json.loads(path.read_text())
    assert doc["meta"]["preset"] == "unit"
    assert doc["slo_gate"]["passed"] is True
    assert doc["windows"] and doc["faults"]
    assert doc["watch_feed"]["events_applied"] == 3


# ---------------------------------------------------------------------------
# the engine end to end (slow: boots the real server)
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_mini_soak_engine_gates_green():
    """A tiny full-stack soak: real server, real sockets, seeded storm.
    The CI-sized version of this runs as `make soak-smoke`."""
    from tools.soak.engine import SoakEngine, SoakSettings

    import tempfile

    artifact = tempfile.mktemp(suffix=".json")
    settings = SoakSettings.smoke(
        duration=12.0, objects=2000, clients=2, target_rps=120.0,
        n_trace_items=1200, artifact=artifact,
        # no restart cycle in the 12 s mini: a warm reboot is longer
        # than the whole window — make soak-smoke carries the
        # restart_storm_survived gate (round 17)
        restarts=0,
    )
    rc = SoakEngine(settings).run()
    doc = json.loads(open(artifact).read())
    assert rc == 0, doc["slo_gate"]
    assert doc["slo_gate"]["passed"] is True
    assert doc["watch_feed"]["events_applied"] > 0
    applied = [f for f in doc["faults"] if f["applied_at"] is not None]
    assert len(applied) >= 3
    assert any(f["kind"] == "sighup" for f in applied)


# ---------------------------------------------------------------------------
# deterministic restart handover (round 19 — the r18 flake's regression)
# ---------------------------------------------------------------------------


def _bare_engine():
    """A SoakEngine shell with only the handover-relevant state — the
    hold/await helpers read nothing else."""
    from tools.soak.engine import SoakEngine

    eng = SoakEngine.__new__(SoakEngine)
    eng._restart_in_progress = False
    return eng


def test_await_handover_holds_until_flag_clears():
    import threading
    import time as _time

    eng = _bare_engine()
    eng._restart_in_progress = True
    released_at = {}

    def clear():
        _time.sleep(0.4)
        eng._restart_in_progress = False
        released_at["t"] = _time.monotonic()

    threading.Thread(target=clear, daemon=True).start()
    t0 = _time.monotonic()
    eng._await_handover(timeout=10.0)
    waited = _time.monotonic() - t0
    assert waited >= 0.35, "probe resumed inside the handover window"
    assert not eng._restart_in_progress


def test_handover_probes_never_observe_the_reboot_window(tmp_path):
    """Seeded end-to-end shape of the r18 flake: a fake server whose
    handover window answers WRONG statuses (the desynced 200/500 the
    soak observed), fronted by the engine's hold + routing-ready gate.
    Probes driven through the gate must only ever see the ready
    answers, across every seed."""
    import json as _json
    import random
    import socket
    import threading
    import time as _time
    from types import SimpleNamespace

    from tools.soak import engine as engine_mod

    body = _json.dumps({"ok": True}).encode()

    def http(status: int) -> bytes:
        reason = {200: "OK", 404: "Not Found", 500: "Error"}[status]
        return (
            f"HTTP/1.1 {status} {reason}\r\n"
            f"Content-Length: {len(body)}\r\n\r\n"
        ).encode() + body

    # fake server: while `window` is set, answers the DESYNCED statuses
    # the r18 flake recorded; after, answers 200s
    window = threading.Event()
    window.set()
    lsock = socket.socket()
    lsock.bind(("127.0.0.1", 0))
    lsock.listen(16)
    port = lsock.getsockname()[1]
    stop = threading.Event()

    def serve():
        lsock.settimeout(0.2)
        while not stop.is_set():
            try:
                conn, _ = lsock.accept()
            except socket.timeout:
                continue
            except OSError:
                return  # listener closed at teardown
            with conn:
                conn.settimeout(2.0)
                try:
                    while not stop.is_set():
                        data = conn.recv(65536)
                        if not data:
                            break
                        status = 500 if window.is_set() else 200
                        conn.sendall(http(status))
                except OSError:
                    pass

    threading.Thread(target=serve, daemon=True).start()
    try:
        for seed in (3, 11, 42):
            rng = random.Random(seed)
            eng = _bare_engine()
            eng.api_port = port
            eng._restart_in_progress = True
            probe = SimpleNamespace(path="/validate/p", body=b"{}")
            eng._restart_probes = [probe]
            # routing flips ready at a seeded moment; the engine's gate
            # (readiness + canary) must absorb it deterministically
            delay = 0.2 + rng.random() * 0.4

            def flip(d=delay):
                _time.sleep(d)
                window.clear()

            window.set()
            t = threading.Thread(target=flip, daemon=True)
            t.start()
            server = SimpleNamespace(
                state=SimpleNamespace(
                    readiness=lambda: (
                        (503, "booting") if window.is_set() else (200, "ok")
                    )
                )
            )
            assert eng._await_routing_ready(server, timeout=30.0)
            eng._restart_in_progress = False
            # the probes the engine releases after the gate: always the
            # ready answer, never the window's desynced one
            results = eng._probe(eng._restart_probes * 4)
            assert [status for _p, status, _b in results] == [200] * 4
            t.join(timeout=5)
    finally:
        stop.set()
        lsock.close()


def test_restart_gate_requires_routing_ready(monkeypatch):
    """The SLO gate fails a restart event whose handover never proved
    routing re-established (pre-round-19 events cannot silently pass)."""
    rec = SLORecorder(window_seconds=5.0)
    rec.record(200, 1.0, "ok")
    ok_event = {
        "warm_boot_used": True,
        "verdicts_bit_exact": True,
        "routing_ready_before_probes": True,
    }
    stale_event = {
        "warm_boot_used": True,
        "verdicts_bit_exact": True,
    }
    good = rec.gate(
        p99_budget_ms=1000.0, fault_events=[], min_fault_events=0,
        restart_storm={"planned": 1, "events": [ok_event]},
    )
    assert good["checks"]["restart_storm_survived"] is True
    bad = rec.gate(
        p99_budget_ms=1000.0, fault_events=[], min_fault_events=0,
        restart_storm={"planned": 1, "events": [stale_event]},
    )
    assert bad["checks"]["restart_storm_survived"] is False
