"""Wall-clock deadline for wasm execution (round-4 VERDICT item 5).

Fuel bounds instructions, not time (round-3 weak #4): a slow-but-
terminating guest could exceed --policy-timeout in real time without
exhausting fuel. The interpreter now checks the clock every 64Ki
instructions against an ambient deadline (interp.deadline_scope), and the
policy layer maps the trip to the reference's "execution deadline
exceeded" in-band rejection (src/lib.rs:176-190)."""

from __future__ import annotations

import time

import pytest

from policy_server_tpu.wasm.interp import (
    Instance,
    WasmDeadlineExceeded,
    deadline_scope,
)
from policy_server_tpu.wasm.wat import assemble

# a guest that never returns: the interpreter must cut it on wall-clock
SPIN_WAPC = """
(module
  (memory (export "memory") 1)
  (func (export "__guest_call") (param $op i32) (param $n i32) (result i32)
    loop $spin
      br $spin
    end
    i32.const 1)
)
"""


def test_interpreter_deadline_cuts_spin_loop():
    module_bytes = assemble(SPIN_WAPC)
    with deadline_scope(0.2):
        inst = Instance(
            __import__(
                "policy_server_tpu.wasm.binary", fromlist=["decode_module"]
            ).decode_module(module_bytes),
            fuel=None,  # unbounded fuel: only the clock can stop it
        )
        t0 = time.perf_counter()
        with pytest.raises(WasmDeadlineExceeded):
            inst.invoke("__guest_call", 0, 0)
        elapsed = time.perf_counter() - t0
    assert 0.1 < elapsed < 2.0  # cut at ~budget, not at fuel exhaustion


def test_no_deadline_without_scope():
    """Outside a scope the fuel limit still terminates runaway guests."""
    from policy_server_tpu.wasm.binary import decode_module
    from policy_server_tpu.wasm.interp import WasmFuelExhausted

    inst = Instance(decode_module(assemble(SPIN_WAPC)), fuel=100_000)
    with pytest.raises(WasmFuelExhausted):
        inst.invoke("__guest_call", 0, 0)


# spins on the 8-byte "validate" op only; any other op (validate_settings,
# protocol_version) answers {"valid":true} — so environment BUILD succeeds
# and the deadline trips at evaluation time
SPIN_ON_VALIDATE_WAPC = """
(module
  (import "wapc" "__guest_response" (func $guest_response (param i32 i32)))
  (memory (export "memory") 1)
  (data (i32.const 8) "{\\22valid\\22:true}")
  (func (export "__guest_call") (param $op_len i32) (param $n i32) (result i32)
    local.get $op_len
    i32.const 8
    i32.eq
    if
      loop $spin
        br $spin
      end
    end
    i32.const 8
    i32.const 14
    call $guest_response
    i32.const 1)
)
"""


def test_wasm_policy_rejected_in_band_at_wall_clock():
    """A spinning wasm POLICY resolves in-band with the reference's
    deadline message at ~policy_timeout, regardless of fuel."""
    from policy_server_tpu.evaluation.wasm_policy import (
        DEADLINE_MESSAGE,
        WasmPolicyModule,
    )

    module = WasmPolicyModule(
        assemble(SPIN_WAPC), name="spin", digest="x", fuel=None,
        wall_clock_budget=0.3,
    )
    program = module.build({})
    t0 = time.perf_counter()
    verdict = program.host_evaluator({"uid": "u1"})
    elapsed = time.perf_counter() - t0
    assert verdict["accepted"] is False
    assert verdict["message"] == DEADLINE_MESSAGE
    assert verdict["code"] == 500
    assert elapsed < 2.0


def test_settings_validation_deadline_cut():
    """validate_settings also executes guest code — a spinning guest must
    not hang environment build; it surfaces as invalid settings."""
    from policy_server_tpu.evaluation.wasm_policy import (
        DEADLINE_MESSAGE,
        WasmPolicyModule,
    )

    module = WasmPolicyModule(
        assemble(SPIN_WAPC), name="spin", digest="x", fuel=None,
        wall_clock_budget=0.3,
    )
    t0 = time.perf_counter()
    resp = module.validate_settings({})
    elapsed = time.perf_counter() - t0
    assert resp.valid is False
    assert DEADLINE_MESSAGE in (resp.message or "")
    assert elapsed < 2.0


def test_wasm_policy_serves_deadline_through_environment():
    """End to end: the builder syncs --policy-timeout onto the module
    (wasm_wall_clock_budget) and a spinning validate is rejected in-band."""
    from policy_server_tpu.evaluation.environment import (
        EvaluationEnvironmentBuilder,
    )
    from policy_server_tpu.evaluation.wasm_policy import (
        DEADLINE_MESSAGE,
        WasmPolicyModule,
    )
    from policy_server_tpu.models import AdmissionReviewRequest, ValidateRequest
    from policy_server_tpu.models.policy import parse_policy_entry

    from conftest import build_admission_review_dict

    module = WasmPolicyModule(
        assemble(SPIN_ON_VALIDATE_WAPC), name="spin", digest="x", fuel=None
    )
    env = EvaluationEnvironmentBuilder(
        backend="jax",
        module_resolver=lambda url: module,
        wasm_wall_clock_budget=0.3,
    ).build({"spin": parse_policy_entry("spin", {"module": "file:///s.wasm"})})
    assert module.wall_clock_budget == 0.3  # builder synced the budget
    req = ValidateRequest.from_admission(
        AdmissionReviewRequest.from_dict(build_admission_review_dict()).request
    )
    t0 = time.perf_counter()
    resp = env.validate("spin", req)
    elapsed = time.perf_counter() - t0
    assert resp.allowed is False
    assert resp.status.code == 500
    assert DEADLINE_MESSAGE in resp.status.message
    assert elapsed < 2.0
