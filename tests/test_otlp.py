"""OTLP export tests against a live in-process gRPC collector fixture —
the analog of the reference's testcontainers OTEL pipeline test
(tests/integration_test.rs:798-973): spans arrive under service
``kubewarden-policy-server`` with the reference field set, trace ids
propagate through the micro-batcher, and both metrics instruments
(``kubewarden_policy_evaluations_total`` + the latency histogram) arrive
over OTLP gRPC."""

from __future__ import annotations

import threading
from concurrent.futures import ThreadPoolExecutor

import grpc
import pytest

from policy_server_tpu.telemetry import metrics as metrics_mod
from policy_server_tpu.telemetry import otlp
from policy_server_tpu.telemetry import otlp_pb2 as pb


class CollectorFixture:
    """In-process OTLP gRPC collector: records every Export request."""

    def __init__(self):
        self.trace_requests: list[pb.ExportTraceServiceRequest] = []
        self.metrics_requests: list[pb.ExportMetricsServiceRequest] = []
        self._event = threading.Event()
        self._server = grpc.server(ThreadPoolExecutor(max_workers=2))

        def export_traces(request, context):
            self.trace_requests.append(request)
            self._event.set()
            return pb.ExportTraceServiceResponse()

        def export_metrics(request, context):
            self.metrics_requests.append(request)
            self._event.set()
            return pb.ExportMetricsServiceResponse()

        self._server.add_generic_rpc_handlers(
            (
                grpc.method_handlers_generic_handler(
                    "opentelemetry.proto.collector.trace.v1.TraceService",
                    {
                        "Export": grpc.unary_unary_rpc_method_handler(
                            export_traces,
                            request_deserializer=(
                                pb.ExportTraceServiceRequest.FromString
                            ),
                            response_serializer=(
                                pb.ExportTraceServiceResponse.SerializeToString
                            ),
                        )
                    },
                ),
                grpc.method_handlers_generic_handler(
                    "opentelemetry.proto.collector.metrics.v1.MetricsService",
                    {
                        "Export": grpc.unary_unary_rpc_method_handler(
                            export_metrics,
                            request_deserializer=(
                                pb.ExportMetricsServiceRequest.FromString
                            ),
                            response_serializer=(
                                pb.ExportMetricsServiceResponse.SerializeToString
                            ),
                        )
                    },
                ),
            )
        )
        self.port = self._server.add_insecure_port("127.0.0.1:0")
        self._server.start()

    @property
    def endpoint(self) -> str:
        return f"http://127.0.0.1:{self.port}"

    def wait(self, timeout: float = 10.0) -> bool:
        ok = self._event.wait(timeout)
        self._event.clear()
        return ok

    def spans(self) -> list[pb.Span]:
        out = []
        for req in self.trace_requests:
            for rs in req.resource_spans:
                for ss in rs.scope_spans:
                    out.extend(ss.spans)
        return out

    def metric_names(self) -> set[str]:
        return {
            m.name
            for req in self.metrics_requests
            for rm in req.resource_metrics
            for sm in rm.scope_metrics
            for m in sm.metrics
        }

    def metric(self, name: str) -> pb.Metric | None:
        for req in self.metrics_requests:
            for rm in req.resource_metrics:
                for sm in rm.scope_metrics:
                    for m in sm.metrics:
                        if m.name == name:
                            return m
        return None

    def resource_service_names(self) -> set[str]:
        out = set()
        for req in list(self.trace_requests) + list(self.metrics_requests):
            containers = getattr(req, "resource_spans", None) or getattr(
                req, "resource_metrics"
            )
            for r in containers:
                for kv in r.resource.attributes:
                    if kv.key == "service.name":
                        out.add(kv.value.string_value)
        return out

    def stop(self):
        self._server.stop(grace=None)


@pytest.fixture()
def collector():
    c = CollectorFixture()
    yield c
    c.stop()
    otlp.shutdown_for_tests()


@pytest.fixture(autouse=True)
def fresh_metrics():
    metrics_mod.reset_metrics_for_tests()
    yield
    metrics_mod.reset_metrics_for_tests()


def test_span_pipeline_exports_to_collector(collector):
    tracer = otlp.install_tracer(collector.endpoint)
    with tracer.start_span("validation") as sp:
        sp.set_attributes(
            {
                "policy_id": "priv",
                "request_uid": "uid-1",
                "allowed": False,
                "response_code": 500,
            }
        )
        parent_ctx = sp.context
        # child span on another thread, parented explicitly — the batcher
        # propagation pattern
        t = threading.Thread(
            target=otlp.emit_span,
            args=(
                "policy_evaluation",
                parent_ctx,
                None,
                {"policy_id": "priv", "batch_size": 4},
            ),
        )
        t.start()
        t.join()
    otlp._processor.force_flush()  # noqa: SLF001 — test drives the flush
    assert collector.wait()

    spans = collector.spans()
    names = {s.name for s in spans}
    assert {"validation", "policy_evaluation"} <= names
    assert collector.resource_service_names() == {"kubewarden-policy-server"}
    val = next(s for s in spans if s.name == "validation")
    child = next(s for s in spans if s.name == "policy_evaluation")
    # trace-id propagation: same trace, parented on the validation span
    assert child.trace_id == val.trace_id
    assert child.parent_span_id == val.span_id
    attrs = {kv.key: kv.value for kv in val.attributes}
    assert attrs["policy_id"].string_value == "priv"
    assert attrs["allowed"].bool_value is False
    assert attrs["response_code"].int_value == 500


def test_metrics_push_delivers_both_instruments(collector):
    registry = metrics_mod.setup_metrics()
    m = metrics_mod.PolicyEvaluation(
        policy_name="priv",
        policy_mode="protect",
        resource_kind="Pod",
        resource_namespace="default",
        resource_request_operation="CREATE",
        accepted=True,
        mutated=False,
        request_origin="validate",
    )
    registry.add_policy_evaluation(m)
    registry.record_policy_latency(3.5, m)

    pusher = otlp.OtlpMetricsPusher(
        registry, otlp.OtlpExporter(collector.endpoint), interval_seconds=3600
    )
    try:
        assert pusher.push_once()
        assert collector.wait()
        names = collector.metric_names()
        assert metrics_mod.EVALUATIONS_TOTAL in names
        assert metrics_mod.LATENCY_MILLISECONDS in names

        total = collector.metric(metrics_mod.EVALUATIONS_TOTAL)
        assert total.sum.is_monotonic
        point = total.sum.data_points[0]
        assert point.as_double == 1.0
        labels = {kv.key: kv.value.string_value for kv in point.attributes}
        assert labels["policy_name"] == "priv"
        assert labels["accepted"] == "true"

        hist = collector.metric(metrics_mod.LATENCY_MILLISECONDS)
        dp = hist.histogram.data_points[0]
        assert dp.count == 1
        assert dp.sum == pytest.approx(3.5)
        assert len(dp.bucket_counts) == len(dp.explicit_bounds) + 1
        assert sum(dp.bucket_counts) == dp.count
    finally:
        pusher.shutdown()


def test_batcher_emits_child_spans_with_propagated_trace_id(collector):
    """End-to-end: a span opened around batcher submission yields an
    exported child policy_evaluation span in the same trace."""
    from policy_server_tpu.api.service import RequestOrigin
    from policy_server_tpu.evaluation.environment import (
        EvaluationEnvironmentBuilder,
    )
    from policy_server_tpu.models import AdmissionReviewRequest, ValidateRequest
    from policy_server_tpu.models.policy import parse_policy_entry
    from policy_server_tpu.runtime.batcher import MicroBatcher

    from conftest import build_admission_review_dict

    tracer = otlp.install_tracer(collector.endpoint)
    env = EvaluationEnvironmentBuilder(backend="jax").build(
        {"priv": parse_policy_entry("priv", {"module": "builtin://pod-privileged"})}
    )
    batcher = MicroBatcher(env, host_fastpath_threshold=0, max_batch_size=4, batch_timeout_ms=1.0).start()
    try:
        req = ValidateRequest.from_admission(
            AdmissionReviewRequest.from_dict(build_admission_review_dict()).request
        )
        with tracer.start_span("validation") as sp:
            fut = batcher.submit("priv", req, RequestOrigin.VALIDATE)
            fut.result(timeout=30)
            trace_id = sp.context.trace_id
    finally:
        batcher.shutdown()
        env.close()
    otlp._processor.force_flush()  # noqa: SLF001
    assert collector.wait()
    children = [
        s for s in collector.spans() if s.name == "policy_evaluation"
    ]
    assert children and children[0].trace_id == trace_id


# ---------------------------------------------------------------------------
# W3C traceparent propagation + span-duration parity (round 18)
# ---------------------------------------------------------------------------


def test_parse_traceparent_vectors():
    tid = "4bf92f3577b34da6a3ce929d0e0e4736"
    sid = "00f067aa0ba902b7"
    ctx = otlp.parse_traceparent(f"00-{tid}-{sid}-01")
    assert ctx is not None
    assert ctx.trace_id == bytes.fromhex(tid)
    assert ctx.span_id == bytes.fromhex(sid)
    # tolerated: surrounding whitespace; a FUTURE version with extra
    # fields (only versions > 00 may append fields, W3C §2.2)
    assert otlp.parse_traceparent(f"  01-{tid}-{sid}-01-extra  ") is not None
    # rejected: absent, malformed, reserved version, all-zero ids,
    # version-00 with extra fields, bad flags
    for bad in (
        None,
        "",
        "garbage",
        f"00-{tid}-{sid}",  # missing flags
        f"ff-{tid}-{sid}-01",  # reserved version
        f"00-{'0' * 32}-{sid}-01",  # zero trace id
        f"00-{tid}-{'0' * 16}-01",  # zero span id
        f"00-{tid[:-2]}-{sid}-01",  # short trace id
        f"00-{tid}-{sid}zz-01",  # non-hex
        f"00-{tid}-{sid}-01-extra",  # version 00 forbids extra fields
        f"00-{tid}-{sid}-zz",  # non-hex flags
        f"00-{tid}-{sid}-0",  # short flags
    ):
        assert otlp.parse_traceparent(bad) is None, bad


def test_handler_span_parents_to_incoming_traceparent(collector):
    """The aiohttp handlers pass the parsed traceparent into span():
    the exported request span must join the caller's trace instead of
    starting a fresh root."""
    from policy_server_tpu.telemetry.tracing import span

    otlp.install_tracer(collector.endpoint)
    tid = "4bf92f3577b34da6a3ce929d0e0e4736"
    sid = "00f067aa0ba902b7"
    parent = otlp.parse_traceparent(f"00-{tid}-{sid}-01")
    with span("validation", parent_ctx=parent, policy_id="priv"):
        pass
    otlp._processor.force_flush()  # noqa: SLF001 — test drives the flush
    assert collector.wait()
    val = next(s for s in collector.spans() if s.name == "validation")
    assert val.trace_id == bytes.fromhex(tid)
    assert val.parent_span_id == bytes.fromhex(sid)


def test_span_duration_matches_logged_elapsed_ms(collector):
    """Satellite (round 18): tracing.span() pins the exported end time
    to start + elapsed_ms, so the OTLP duration and the logged
    elapsed_ms agree EXACTLY — previously the context-manager exit
    stamped end time after set_attributes, skewing the export."""
    import time as _time

    from policy_server_tpu.telemetry.tracing import span

    otlp.install_tracer(collector.endpoint)
    with span("validation", policy_id="priv") as fields:
        _time.sleep(0.02)
    otlp._processor.force_flush()  # noqa: SLF001 — test drives the flush
    assert collector.wait()
    val = next(s for s in collector.spans() if s.name == "validation")
    exported_ms = (val.end_time_unix_nano - val.start_time_unix_nano) / 1e6
    assert exported_ms == pytest.approx(fields["elapsed_ms"], abs=1e-6)
    attrs = {kv.key: kv.value for kv in val.attributes}
    assert attrs["elapsed_ms"].double_value == fields["elapsed_ms"]


def test_explicit_end_time_survives_context_exit(collector):
    """ActiveSpan.__exit__ must not overwrite a pinned end time (the
    parity contract's mechanism)."""
    tracer = otlp.install_tracer(collector.endpoint)
    with tracer.start_span("pinned") as sp:
        sp.data.end_unix_nano = sp.data.start_unix_nano + 12345
    otlp._processor.force_flush()  # noqa: SLF001 — test drives the flush
    assert collector.wait()
    pinned = next(s for s in collector.spans() if s.name == "pinned")
    assert (
        pinned.end_time_unix_nano - pinned.start_time_unix_nano == 12345
    )
