"""Seeded GB01 violation: annotated attribute read and written outside
its lock (the check-then-set-outside-lock bug class)."""

import threading


class Counter:
    def __init__(self):
        self._lock = threading.Lock()
        self.value = 0  # guarded-by: _lock
        self.snapshot = None  # graftcheck: lockfree — atomic swap

    def bump(self):
        with self._lock:
            self.value += 1

    def racy_read(self):
        return self.value  # VIOLATION: read outside _lock

    def racy_check_then_set(self):
        if self.value == 0:  # VIOLATION: check outside _lock
            with self._lock:
                self.value = 1

    def fine_lockfree(self):
        return self.snapshot  # lockfree-annotated: not flagged


_glock = threading.Lock()
_registry: dict = {}  # guarded-by: _glock


def register(k, v):
    with _glock:
        _registry[k] = v


def racy_global_read(k):
    return _registry.get(k)  # VIOLATION: module global outside _glock
