"""Seeded OB08 fixture: gamma is never stamped, beta is stamped twice."""

PH_ALPHA = "alpha"
PH_BETA = "beta"
PH_GAMMA = "gamma"

PHASES = (PH_ALPHA, PH_BETA, PH_GAMMA)
