"""Fixture server: no runtime_stats yields (the histogram is the only
exported family)."""


def runtime_stats():
    return iter(())
