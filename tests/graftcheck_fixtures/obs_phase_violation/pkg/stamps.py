"""Seeded stamping sites: alpha once (clean), beta twice (OB08 multi),
gamma never (OB08 unstamped)."""


def serve(rec, flightrec):
    rec.record_phase(flightrec.PH_ALPHA, 0, 1)
    rec.record_phase(flightrec.PH_BETA, 0, 1)


def serve_again(rec, PH_BETA="beta"):
    rec.record_phase(PH_BETA, 0, 1)  # second site for beta
