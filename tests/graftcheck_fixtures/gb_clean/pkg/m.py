"""Clean guarded-by fixture: every access of the annotated attribute is
under the lock, via a holds-annotated helper, or construction-time."""

import threading


class Counter:
    def __init__(self):
        self._lock = threading.Lock()
        self.value = 0  # guarded-by: _lock

    def bump(self):
        with self._lock:
            self.value += 1

    def read(self):
        with self._lock:
            return self.value

    def _double_locked(self):
        self.value *= 2  # _locked suffix: caller holds the lock

    def helper(self):  # holds: _lock
        return self.value


_glock = threading.Lock()
_registry: dict = {}  # guarded-by: _glock
# graftcheck: lockfree — single bool gate, stale reads acceptable
_armed = False


def register(k, v):
    with _glock:
        _registry[k] = v


def read(k):
    with _glock:
        return _registry.get(k)


def shadowing_local():
    _registry = {}  # a LOCAL, shadows the global: not checked
    return _registry


def gate():
    return _armed  # lockfree-annotated: not checked
