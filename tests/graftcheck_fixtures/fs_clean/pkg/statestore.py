"""Clean FS01 fixture: every raw write lives inside annotated atomic
helpers; callers route through them."""

import os


def atomic_write_bytes(path, data):  # graftcheck: fs-atomic
    tmp = str(path) + ".tmp"
    with open(tmp, "wb") as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def quarantine(path, dest):  # graftcheck: fs-atomic
    os.replace(path, dest)


def persist(path, payload):
    atomic_write_bytes(path, payload)


def load(path):
    with open(path, "rb") as f:
        return f.read()
