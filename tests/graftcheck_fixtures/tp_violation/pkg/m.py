"""Seeded trace-purity violations: a jit root that reads the wall clock
through a helper (TP01), branches on a traced parameter (TP02), and a
device fetch outside the choke points (TP03)."""

import time

import jax


def _impure_helper(x):
    return x * time.time()  # TP01: wall clock frozen into the trace


def forward(features):
    if features:  # TP02: Python branch on a traced parameter
        return _impure_helper(features)
    return features


fused = jax.jit(forward)


def sneaky_fetch(dev_out):
    return jax.device_get(dev_out)  # TP03: outside _device_fetch/_device_call
