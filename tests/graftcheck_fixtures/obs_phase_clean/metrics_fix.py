"""Clean fixture metrics: the histogram is on a dashboard panel."""

import prometheus_client

FIXTURE_PHASE_SECONDS = "policy_server_fixture_phase_seconds"

_h = prometheus_client.Histogram(
    FIXTURE_PHASE_SECONDS, "fixture phase histogram", ("phase",)
)
