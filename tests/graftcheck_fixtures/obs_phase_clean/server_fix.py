"""Fixture server: no runtime_stats yields."""


def runtime_stats():
    return iter(())
