"""Clean OB08 fixture: every phase stamped by exactly one site."""

PH_ALPHA = "alpha"
PH_BETA = "beta"

PHASES = (PH_ALPHA, PH_BETA)
