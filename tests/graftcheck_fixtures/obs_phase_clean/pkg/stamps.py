"""Clean stamping sites: one record_phase call per PHASES member."""


def serve(rec, flightrec):
    rec.record_phase(flightrec.PH_ALPHA, 0, 1)
    rec.record_phase(flightrec.PH_BETA, 0, 1)
