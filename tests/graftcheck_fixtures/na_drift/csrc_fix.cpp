// Seeded ABI-drift fixture: every construct here is wrong on exactly
// one axis; test_graftcheck.py pins the finding each one must yield.
#include <cstdint>
#include <cstring>

// drifted layout: C packs {u32, u16, u8}, binding_fix._HDR says "<IHH"
// graftcheck: abi(binding_fix.py:_HDR)
struct NatHdr {
  uint32_t len;
  uint16_t kind;
  uint8_t flags;
} __attribute__((packed));

// packed wire struct with no abi anchor at all
struct Orphan {
  uint64_t a;
} __attribute__((packed));

extern "C" {

void* nat_create(int fd) {
  (void)fd;
  return nullptr;
}

int64_t nat_poll(void* h, uint8_t* buf, int64_t cap) {
  (void)h;
  (void)buf;
  (void)cap;
  return 0;
}

}  // extern "C"
