"""Seeded drifted bindings for the NA fixture (see csrc_fix.cpp)."""

import ctypes
import struct

# drifted: the C NatHdr packs {u32, u16, u8}; this claims {u32, u16, u16}
_HDR = struct.Struct("<IHH")

lib = ctypes.CDLL("libnat.so")

# no matching extern "C" export at all
lib.nat_missing.argtypes = [ctypes.c_void_p]
lib.nat_missing.restype = ctypes.c_int

# arg2 is int64_t in C but bound as c_int; the int64_t return has no
# declared restype (ctypes' implicit c_int truncates it)
lib.nat_poll.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int]


def frame(n):
    # inline wire-format literal: the layout's second spelling
    return struct.pack("<I", n)
