"""Failpoint fixture package: two compiled-in sites — one armed by the
fixture tests, one not (FP02)."""

from policy_server_tpu import failpoints


def fetch():
    failpoints.fire("site.armed")


def encode():
    failpoints.fire("site.unarmed")  # FP02: no test arms this


def stream():
    failpoints.fire("site.chaosed")  # armed by test_resilience_arming
