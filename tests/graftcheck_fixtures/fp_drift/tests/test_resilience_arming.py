"""Fixture chaos suite: arming from a test_resilience* file satisfies
FP04 for its site (site.chaosed stays clean)."""

from policy_server_tpu import failpoints


def test_chaosed():
    with failpoints.active("site.chaosed", lambda: None):
        pass
