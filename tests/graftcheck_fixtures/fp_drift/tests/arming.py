"""Fixture tests arming one real site and one phantom site (FP01)."""

from policy_server_tpu import failpoints


def test_armed():
    with failpoints.active("site.armed", lambda: None):
        pass


def test_phantom():
    failpoints.set_failpoint("site.phantom", lambda: None)  # FP01
    failpoints.configure("site.armed=raise:boom*1")
