// Clean ABI fixture: the same constructs as na_drift with every axis
// consistent — must produce zero findings.
#include <cstdint>
#include <cstring>

// graftcheck: abi(binding_fix.py:_HDR)
struct NatHdr {
  uint32_t len;
  uint16_t kind;
  uint16_t flags;
} __attribute__((packed));

// offsets-mode anchor: hand-rolled fixed-header reads pinned to _REC2
// graftcheck: abi(binding_fix.py:_REC2)
static bool parse_hdr(const uint8_t* buf, int64_t len, int64_t off) {
  if (len - off < 8) return false;
  uint32_t a;
  uint32_t b;
  memcpy(&a, buf + off, 4);
  memcpy(&b, buf + off + 4, 4);
  off += 8;
  return a <= b;
}

extern "C" {

void* nat_create(int fd) {
  (void)fd;
  return nullptr;
}

int64_t nat_poll(void* h, uint8_t* buf, int64_t cap) {
  (void)h;
  (void)buf;
  (void)cap;
  return 0;
}

}  // extern "C"
