"""Consistent bindings for the clean NA fixture (see csrc_fix.cpp)."""

import ctypes
import struct

_HDR = struct.Struct("<IHH")
_REC2 = struct.Struct("<II")

lib = ctypes.CDLL("libnat.so")

lib.nat_create.argtypes = [ctypes.c_int]
lib.nat_create.restype = ctypes.c_void_p

lib.nat_poll.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int64]
lib.nat_poll.restype = ctypes.c_int64


def frame(n, a, b):
    # module-level Struct constants are the approved spelling
    return _HDR.pack(n, a, b) + _REC2.pack(a, b)
