"""Clean trace-purity fixture: pure jit root; device sync only inside
the _device_fetch choke point."""

import jax
import jax.numpy as jnp


def forward(features):
    return jnp.where(features > 0, features, -features)


fused = jax.jit(forward)


def _device_fetch(dev_out):
    return jax.device_get(dev_out)  # choke point: allowed
