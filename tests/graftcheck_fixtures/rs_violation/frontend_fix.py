"""RS fixture (violation): the classification carries a stale entry
(``patch`` is not on this fixture's model)."""

NATIVE_RESPONSE_FIELDS = frozenset({"uid", "allowed", "status", "patch"})
PYTHON_ONLY_RESPONSE_FIELDS = frozenset()
NATIVE_STATUS_FIELDS = frozenset({"message", "code"})
PYTHON_ONLY_STATUS_FIELDS: frozenset = frozenset()
