// RS fixture (violation): the emitter writes code before message —
// byte order can never match json.dumps of the model's to_dict.
static bool parse_verdict_record(int x) {
  std::string resp;
  resp += "{\"uid\": ";
  resp += ", \"allowed\": ";
  resp += ", \"status\": {";
  resp += "\"code\": ";
  resp += "\"message\": ";
  resp += "}";
  return true;
}
