"""RS fixture (violation): AdmissionResponse gained a ``priority``
field that nobody classified."""


def _drop_none(d):
    return {k: v for k, v in d.items() if v is not None}


class ValidationStatus:
    def to_dict(self):
        return _drop_none(
            {
                "message": self.message,
                "code": self.code,
            }
        )


class AdmissionResponse:
    def to_dict(self):
        return _drop_none(
            {
                "uid": self.uid,
                "allowed": self.allowed,
                "priority": self.priority,  # unclassified (RS01)
                "status": self.status.to_dict() if self.status else None,
            }
        )
