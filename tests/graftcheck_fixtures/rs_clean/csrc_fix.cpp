// RS fixture (clean): keys in to_dict order.
static bool parse_verdict_record(int x) {
  std::string resp;
  resp += "{\"uid\": ";
  resp += ", \"allowed\": ";
  resp += ", \"status\": {";
  resp += "\"message\": ";
  resp += ", \"code\": ";
  resp += "}";
  return true;
}
