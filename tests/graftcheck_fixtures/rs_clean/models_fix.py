"""RS fixture (clean): every to_dict field classified, emitter in
to_dict order."""


def _drop_none(d):
    return {k: v for k, v in d.items() if v is not None}


class ValidationStatus:
    def to_dict(self):
        return _drop_none(
            {
                "message": self.message,
                "code": self.code,
            }
        )


class AdmissionResponse:
    def to_dict(self):
        return _drop_none(
            {
                "uid": self.uid,
                "allowed": self.allowed,
                "status": self.status.to_dict() if self.status else None,
                "auditAnnotations": self.audit_annotations,
            }
        )
