"""RS fixture (clean): total classification."""

NATIVE_RESPONSE_FIELDS = frozenset({"uid", "allowed", "status"})
PYTHON_ONLY_RESPONSE_FIELDS = frozenset({"audit_annotations"})
NATIVE_STATUS_FIELDS = frozenset({"message", "code"})
PYTHON_ONLY_STATUS_FIELDS: frozenset = frozenset()
