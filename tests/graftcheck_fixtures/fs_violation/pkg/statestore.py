"""Seeded FS01 violations: raw writes in a statestore module outside
the annotated atomic helper."""

import os


def atomic_write_bytes(path, data):  # graftcheck: fs-atomic
    tmp = str(path) + ".tmp"
    with open(tmp, "wb") as f:  # blessed: inside the annotated helper
        f.write(data)
    os.replace(tmp, path)  # blessed


def sneaky_direct_write(path, data):
    with open(path, "wb") as f:  # FS01: raw write, no atomicity
        f.write(data)


def sneaky_path_write(path, text):
    path.write_text(text)  # FS01: Path.write_text outside the helper


def sneaky_rename(src, dst):
    os.rename(src, dst)  # FS01: rename is the commit step — helper-only


def reader_is_fine(path):
    with open(path, "rb") as f:  # reads are not writes
        return f.read()
