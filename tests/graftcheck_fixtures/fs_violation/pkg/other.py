"""Seeded FS01 violation: a module OUTSIDE statestore.py writing into
the state dir behind the atomic helper's back."""


def spill_behind_the_helpers_back(state_dir, data):
    (state_dir / "rogue.bin").write_bytes(data)  # FS01: state_dir write


def unrelated_write(tmp_path, data):
    # no state_dir reference: other modules' ordinary file writes are
    # not this checker's business
    (tmp_path / "scratch.bin").write_bytes(data)
