"""Observability fixture metrics module: one live constant, one dead
constant (OB03), and the label schema tuples."""

GOOD_COUNTER = "policy_server_fixture_good"
GOOD_GAUGE = "policy_server_fixture_depth"
DEAD_METRIC = "policy_server_fixture_dead"  # OB03: never registered
# OB07 coverage: env_fix.py's 'covered_stat' maps here; 'phantom_stat'
# and 'ghost_kernel_stat' have no constants (seeded OB07 drift)
COVERED_STAT = "policy_server_predicate_covered_stat"

_EVAL_LABELS = ("policy_name", "accepted")
_INIT_LABELS = ("policy_name", "initialization_error")
