"""Observability fixture: stats-dict key schemas (OB07). The
'phantom_stat' key has no policy_server_predicate_phantom_stat constant
in metrics_fix.py — seeded OB07 drift; 'covered_stat' does."""

OPTIMIZER_STAT_KEYS = (
    "covered_stat",
    "phantom_stat",
)
PALLAS_STAT_KEYS = (
    "ghost_kernel_stat",
)
