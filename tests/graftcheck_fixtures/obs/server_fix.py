"""Observability fixture server module: a runtime_stats provider with a
constant-named counter, a literal-named counter (OB01), a gauge, and a
histogram-kind yield the collector cannot export (OB02)."""

from tests.graftcheck_fixtures.obs import metrics_fix as metrics_names


def runtime_stats():
    yield (metrics_names.GOOD_COUNTER, "counter", "fine", 1)
    yield (metrics_names.GOOD_GAUGE, "gauge", "fine", 2)
    yield ("policy_server_fixture_literal", "counter", "OB01", 3)
    yield (metrics_names.GOOD_COUNTER, "histogram", "OB02", 4)


def runtime_stats_computed():
    pass


def _more():
    # second provider shape: computed names must be rejected (OB01)
    def runtime_stats():
        yield ("policy_server_" + "computed", "counter", "OB01-computed", 5)
