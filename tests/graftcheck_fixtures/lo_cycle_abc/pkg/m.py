"""Seeded LO01 3-lock cycle: one path acquires A->B->C (the B->C edge
through a method call), another C->A — the ABC/BCA inversion."""

import threading


class Router:
    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()
        self._c = threading.Lock()

    def path_ab(self):
        with self._a:
            with self._b:
                pass

    def path_bc(self):
        with self._b:
            self._take_c()

    def _take_c(self):
        with self._c:
            pass

    def path_ca(self):  # closes the cycle: C held, then A
        with self._c:
            with self._a:
                pass
