// Seeded wire-bounds violations: each function is wrong on exactly one
// axis; test_graftcheck.py pins the finding each one must yield.
#include <cstdint>
#include <cstring>
#include <string>
#include <vector>

// NW01: a memcpy'd wire length drives resize with no dominating check
// graftcheck: wire-input
static bool parse_rec(const uint8_t* buf, int64_t len) {
  (void)len;
  int64_t off = 0;
  uint32_t n;
  memcpy(&n, buf + off, 4);
  std::vector<uint8_t> v;
  v.resize(n);
  return true;
}

// NW02: banned unbounded copy primitive (flagged file-wide, no
// wire-input annotation needed)
static void copy_name(char* dst, const char* src) {
  strcpy(dst, src);
}

// NW03: narrowing cast of a size_t-valued .size() with no dominating
// range check
// graftcheck: wire-input
static uint16_t header_len(const std::string& out) {
  uint16_t plen = (uint16_t)out.size();
  return plen;
}
