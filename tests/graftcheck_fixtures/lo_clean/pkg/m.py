"""Clean lock-order fixture: a consistent A->B->C acquisition order
(no back edge, no cycle)."""

import threading


class Router:
    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()
        self._c = threading.Lock()

    def path_ab(self):
        with self._a:
            with self._b:
                pass

    def path_bc(self):
        with self._b:
            with self._c:
                pass

    def path_abc(self):
        with self._a:
            with self._b:
                with self._c:
                    pass
