// Clean wire-bounds fixture: the same shapes as nw_violation with the
// guards in place, plus one bounds-ok escape — must yield zero findings.
#include <cstdio>
#include <cstdint>
#include <cstring>
#include <string>
#include <vector>

// the memcpy'd length is range-checked before it reaches resize
// graftcheck: wire-input
static bool parse_rec(const uint8_t* buf, int64_t len) {
  int64_t off = 0;
  uint32_t n;
  memcpy(&n, buf + off, 4);
  off += 4;
  if ((int64_t)n > len - off) return false;
  std::vector<uint8_t> v;
  v.resize(n);
  return true;
}

// bounded replacement for the banned primitive
static void copy_name(char* dst, size_t cap, const char* src) {
  snprintf(dst, cap, "%s", src);
}

// the take(n, p) lambda idiom: passing a tainted count to a
// locally-defined bounds-checking lambda counts as the dominating check
// graftcheck: wire-input
static bool parse_fields(const uint8_t* buf, int64_t len) {
  int64_t off = 0;
  auto take = [&](int64_t n, const uint8_t*& p) {
    if (off + n > len) return false;
    p = buf + off;
    off += n;
    return true;
  };
  uint32_t flen;
  memcpy(&flen, buf + off, 4);
  off += 4;
  const uint8_t* fld;
  if (!take(flen, fld)) return false;
  std::string s((const char*)fld, (size_t)flen);
  return true;
}

// narrowing cast dominated by an explicit range check
// graftcheck: wire-input
static uint16_t header_len(const std::string& out) {
  if (out.size() > 0xFFFF) return 0;
  uint16_t plen = (uint16_t)out.size();
  return plen;
}

// the escape hatch: a cast the analysis would flag, annotated with why
// it is safe
// graftcheck: wire-input
static uint16_t digest_len(const std::string& out) {
  // graftcheck: bounds-ok(digest strings are fixed 32-byte hex)
  uint16_t dlen = (uint16_t)out.size();
  return dlen;
}
