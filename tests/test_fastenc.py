"""Native encoder differential tests: the C++ encoder (csrc/fastenc.cpp)
must be bit-exact vs the Python trie encoder on every feature array, across
the synthetic firehose, unicode/escape torture, overflow routing, and the
batch API. Skipped when no C++ toolchain is available."""

from __future__ import annotations

import numpy as np
import pytest

# flagship_policies() builds signature-capability policies that need
# cryptography at runtime; dependency-light containers skip the module
pytest.importorskip("cryptography")

from policy_server_tpu.evaluation.environment import EvaluationEnvironmentBuilder
from policy_server_tpu.models import AdmissionReviewRequest, ValidateRequest
from policy_server_tpu.models.policy import parse_policy_entry
from policy_server_tpu.ops import fastenc
from policy_server_tpu.policies.flagship import flagship_policies, synthetic_firehose

pytestmark = pytest.mark.skipif(
    not fastenc.native_available(), reason="native encoder unavailable"
)


@pytest.fixture(scope="module")
def env():
    return EvaluationEnvironmentBuilder(backend="jax").build(flagship_policies())


def to_request(doc: dict) -> ValidateRequest:
    return ValidateRequest.from_admission(
        AdmissionReviewRequest.from_dict(doc).request
    )


def assert_encodings_equal(schema, table, payload) -> None:
    py = schema.encode(payload, table)
    nat = schema.native.encode(payload, table)
    assert py.keys() == nat.keys()
    for k in py:
        assert np.array_equal(py[k], nat[k]), k


def test_differential_firehose(env):
    schema = env.schemas[0]
    for doc in synthetic_firehose(200, seed=9):
        assert_encodings_equal(schema, env.table, to_request(doc).payload())


def test_differential_unicode_and_escapes(env):
    doc = synthetic_firehose(1, seed=1)[0]
    doc["request"]["object"]["metadata"]["labels"] = {
        "app": "café-☃️",
        'quote"key': "line1\nline2\tend \U0001f600",
        "backslash\\key": "nul ctrl",
    }
    doc["request"]["object"]["metadata"]["annotations"] = {
        "prod.example.com/debug": "true"
    }
    for schema in env.schemas:
        assert_encodings_equal(schema, env.table, to_request(doc).payload())


def test_differential_type_mismatches(env):
    doc = synthetic_firehose(1, seed=2)[0]
    pod = doc["request"]["object"]
    # wrong-typed leaves must read as missing on both paths
    pod["spec"]["containers"][0]["image"] = 42
    pod["spec"]["hostNetwork"] = "yes"
    pod["spec"]["containers"][0]["securityContext"] = {"privileged": "true"}
    pod["metadata"]["labels"] = None
    for schema in env.schemas:
        assert_encodings_equal(schema, env.table, to_request(doc).payload())


def test_batch_api_matches_single(env):
    schema = env.schemas[0]
    docs = synthetic_firehose(17, seed=5)
    blobs = [to_request(d).payload_json() for d in docs]
    packed, status = schema.native.encode_batch(blobs, 32, env.table)
    assert (status == 0).all()
    batch = schema.unpack_host(packed)
    for row, d in enumerate(docs):
        single = schema.native.encode(to_request(d).payload(), env.table)
        for k, arr in single.items():
            assert np.array_equal(batch[k][row], arr), k


def test_batch_overflow_rows_flagged_and_zeroed(env):
    schema = env.schemas[0]  # caps 8/4
    ok_doc = synthetic_firehose(1, seed=6)[0]
    big_doc = synthetic_firehose(1, seed=7)[0]
    big_doc["request"]["object"]["spec"]["containers"] = [
        {"name": f"c{i}", "image": "nginx"} for i in range(12)  # > cap 8
    ]
    blobs = [to_request(ok_doc).payload_json(), to_request(big_doc).payload_json()]
    packed, status = schema.native.encode_batch(blobs, 2, env.table)
    assert status[0] == 0 and status[1] < 0
    # the failed row must read all-missing
    for k, arr in schema.unpack_host(packed).items():
        if arr.ndim >= 1 and arr.shape[0] == 2:
            assert not arr[1].any(), k


def test_native_verdicts_match_oracle(env):
    """End-to-end: native-encoded device verdicts == host oracle verdicts."""
    oracle_env = EvaluationEnvironmentBuilder(backend="oracle").build(
        flagship_policies()
    )
    docs = synthetic_firehose(64, seed=8)
    items = [("pod-security-group", to_request(d)) for d in docs]
    jax_results = env.validate_batch(items)
    oracle_results = oracle_env.validate_batch(
        [("pod-security-group", to_request(d)) for d in docs]
    )
    for a, b in zip(jax_results, oracle_results):
        assert a.to_dict() == b.to_dict()


def test_out_of_range_int_routes_to_oracle(tmp_path):
    """Regression (fail-open): an int that doesn't fit int32 must not
    truncate or read as missing — both encoders fail the encode and the
    environment answers via the oracle, matching oracle semantics."""
    import json

    from policy_server_tpu.config.config import Config
    from policy_server_tpu.fetch import dump_artifact, make_module_resolver
    from policy_server_tpu.ops import ir
    from policy_server_tpu.ops.codec import SchemaOverflow
    from policy_server_tpu.ops.compiler import Rule
    from policy_server_tpu.ops.ir import DType, Path as IRPath

    src = tmp_path / "cap.tpp.json"
    src.write_text(
        json.dumps(
            dump_artifact(
                "replica-cap",
                [
                    Rule(
                        "cap",
                        ir.gt(IRPath("object.spec.replicas", DType.I32), 3),
                        "too many replicas",
                    )
                ],
            )
        )
    )
    policies = {
        "replica-cap": parse_policy_entry(
            "replica-cap", {"module": f"file://{src}"}
        )
    }
    config = Config(policies=policies, policies_download_dir=str(tmp_path / "s"))
    jax_env = EvaluationEnvironmentBuilder(
        backend="jax", module_resolver=make_module_resolver(config)
    ).build(policies)

    doc = synthetic_firehose(1, seed=3)[0]
    doc["request"]["object"]["spec"] = {"replicas": 2**33}  # >> int32
    req = to_request(doc)

    # python encoder refuses
    with pytest.raises(SchemaOverflow):
        jax_env.schemas[-1].encode(req.payload(), jax_env.table)
    # native batch flags the row
    _, status = jax_env.schemas[-1].native.encode_batch(
        [req.payload_json()], 1, jax_env.table
    )
    assert status[0] != 0
    # end to end: verdict comes from the oracle and REJECTS (2**33 > 3)
    before = jax_env.oracle_fallbacks
    resp = jax_env.validate_batch([("replica-cap", req)])[0]
    assert not resp.allowed and resp.status.message == "too many replicas"
    assert jax_env.oracle_fallbacks == before + 1
