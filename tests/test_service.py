"""api.service unit tests with a scripted mock environment — the analog of
the reference's mockall seam (src/evaluation/evaluation_environment.rs:31-32,
src/api/service.rs:224-283): the service layer is exercised with NO device
work at all. Mode/origin matrix mirrors service.rs:568-635."""

from __future__ import annotations

import base64
import json

import pytest

from policy_server_tpu.api.service import (
    RequestOrigin,
    evaluate,
    validation_response_with_constraints,
)
from policy_server_tpu.evaluation.errors import (
    PolicyInitializationError,
    PolicyNotFoundError,
)
from policy_server_tpu.models import (
    AdmissionResponse,
    AdmissionReviewRequest,
    ValidateRequest,
    ValidationStatus,
)
from policy_server_tpu.models.policy import PolicyMode
from policy_server_tpu.telemetry import metrics as metrics_mod

from conftest import build_admission_review_dict


class MockEnvironment:
    """Duck-typed EvaluationEnvironment with scripted answers."""

    def __init__(
        self,
        response: AdmissionResponse | Exception = None,
        policy_mode: PolicyMode = PolicyMode.PROTECT,
        allowed_to_mutate: bool = False,
        always_accept_namespace: str | None = None,
    ):
        self._response = response
        self._mode = policy_mode
        self._allowed_to_mutate = allowed_to_mutate
        self.always_accept_namespace = always_accept_namespace
        self.validate_calls = 0

    def get_policy_mode(self, policy_id):
        return self._mode

    def get_policy_allowed_to_mutate(self, policy_id):
        return self._allowed_to_mutate

    def should_always_accept_requests_made_inside_of_namespace(self, ns):
        return self.always_accept_namespace is not None and ns == self.always_accept_namespace

    def validate(self, policy_id, request):
        self.validate_calls += 1
        if isinstance(self._response, Exception):
            raise self._response
        return self._response


@pytest.fixture(autouse=True)
def fresh_metrics():
    metrics_mod.reset_metrics_for_tests()
    yield
    metrics_mod.reset_metrics_for_tests()


def make_request() -> ValidateRequest:
    review = AdmissionReviewRequest.from_dict(build_admission_review_dict())
    return ValidateRequest.from_admission(review.request)


def patch_b64(ops) -> str:
    return base64.b64encode(json.dumps(ops).encode()).decode()


REJECTION = AdmissionResponse(
    uid="hello",
    allowed=False,
    status=ValidationStatus(message="nope", code=400),
)


def test_protect_mode_passes_through_rejection():
    env = MockEnvironment(response=REJECTION.copy())
    resp = evaluate(env, "p1", make_request(), RequestOrigin.VALIDATE)
    assert not resp.allowed
    assert resp.status.message == "nope"


def test_monitor_mode_always_allows_and_strips_everything():
    env = MockEnvironment(response=REJECTION.copy(), policy_mode=PolicyMode.MONITOR)
    resp = evaluate(env, "p1", make_request(), RequestOrigin.VALIDATE)
    assert resp.allowed
    assert resp.status is None and resp.patch is None
    # metrics recorded the VANILLA verdict (service.rs:99-104)
    reg = metrics_mod.default_registry()
    assert reg.counter_value(
        metrics_mod.EVALUATIONS_TOTAL, {"accepted": "false", "policy_mode": "monitor"}
    ) == 1


def test_audit_origin_reports_raw_verdict_even_in_monitor_mode():
    env = MockEnvironment(response=REJECTION.copy(), policy_mode=PolicyMode.MONITOR)
    resp = evaluate(env, "p1", make_request(), RequestOrigin.AUDIT)
    assert not resp.allowed
    assert resp.status.message == "nope"


def test_protect_not_allowed_to_mutate_rejects_patched_response():
    mutated = AdmissionResponse(uid="hello", allowed=True, patch=patch_b64([{"op": "add"}]))
    mutated.patch_type = "JSONPatch"
    env = MockEnvironment(response=mutated, allowed_to_mutate=False)
    resp = evaluate(env, "p1", make_request(), RequestOrigin.VALIDATE)
    assert not resp.allowed
    assert resp.patch is None and resp.patch_type is None
    assert "currently configured to not allow mutations" in resp.status.message
    assert "Request rejected by policy p1." in resp.status.message


def test_protect_allowed_to_mutate_passes_patch():
    mutated = AdmissionResponse(uid="hello", allowed=True, patch=patch_b64([{"op": "add"}]))
    env = MockEnvironment(response=mutated, allowed_to_mutate=True)
    resp = evaluate(env, "p1", make_request(), RequestOrigin.VALIDATE)
    assert resp.allowed and resp.patch is not None


def test_always_accept_namespace_short_circuits():
    env = MockEnvironment(
        response=REJECTION.copy(), always_accept_namespace="my-namespace"
    )
    resp = evaluate(env, "p1", make_request(), RequestOrigin.VALIDATE)
    assert resp.allowed
    assert resp.uid == "hello"
    assert env.validate_calls == 0
    reg = metrics_mod.default_registry()
    assert reg.counter_value(
        metrics_mod.EVALUATIONS_TOTAL, {"accepted": "true"}
    ) == 1


def test_initialization_error_becomes_500_in_band():
    env = MockEnvironment(response=PolicyInitializationError("p1", "boom"))
    resp = evaluate(env, "p1", make_request(), RequestOrigin.VALIDATE)
    assert not resp.allowed
    assert resp.status.code == 500 and "boom" in resp.status.message
    reg = metrics_mod.default_registry()
    assert reg.counter_value(metrics_mod.INIT_ERRORS_TOTAL) == 1


def test_policy_not_found_propagates():
    env = MockEnvironment(response=PolicyNotFoundError("nope"))
    with pytest.raises(PolicyNotFoundError):
        evaluate(env, "nope", make_request(), RequestOrigin.VALIDATE)


def test_raw_request_records_raw_metric():
    env = MockEnvironment(response=AdmissionResponse(uid="u1", allowed=True))
    req = ValidateRequest.from_raw({"uid": "u1", "anything": 1})
    resp = evaluate(env, "p1", req, RequestOrigin.VALIDATE)
    assert resp.allowed
    reg = metrics_mod.default_registry()
    assert reg.counter_value(
        metrics_mod.EVALUATIONS_TOTAL, {"request_origin": "validate_raw"}
    ) == 1
    assert len(reg.latency_samples({"request_origin": "validate_raw"})) == 1


@pytest.mark.parametrize(
    "mode,allowed_to_mutate,has_patch,expect_allowed,expect_patch",
    [
        (PolicyMode.PROTECT, True, True, True, True),
        (PolicyMode.PROTECT, False, True, False, False),
        (PolicyMode.PROTECT, False, False, True, False),
        (PolicyMode.MONITOR, False, True, True, False),
        (PolicyMode.MONITOR, True, True, True, False),
    ],
)
def test_constraint_matrix(mode, allowed_to_mutate, has_patch, expect_allowed, expect_patch):
    resp = AdmissionResponse(uid="u", allowed=True)
    if has_patch:
        resp.patch = patch_b64([{"op": "remove", "path": "/x"}])
        resp.patch_type = "JSONPatch"
    out = validation_response_with_constraints("pol", mode, allowed_to_mutate, resp)
    assert out.allowed is expect_allowed
    assert (out.patch is not None) is expect_patch
