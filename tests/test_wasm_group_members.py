"""Wasm policies as policy-group members (round-4 VERDICT item 3).

The reference composes ANY loaded policy into groups
(src/evaluation/evaluation_environment.rs:596-651). Here, host-executed
wasm members contribute their verdict bits as device inputs to the fused
group reduction (WASM_BITS_KEY): the wasm engine runs at encode time, the
boolean expression still evaluates on-device, and causes/mutation-ban
semantics match IR members. These tests mix a real WAT-authored waPC
wasm member with IR members and pin verdicts, causes, the evaluated-mask
semantics, the mutation ban, and agreement across every execution path
(device batch, single validate, host fast-path, oracle backend)."""

from __future__ import annotations

import pytest

from policy_server_tpu.evaluation.environment import EvaluationEnvironmentBuilder
from policy_server_tpu.fetch.artifact import load_artifact
from policy_server_tpu.models import AdmissionReviewRequest, ValidateRequest
from policy_server_tpu.models.policy import parse_policy_entry
from policy_server_tpu.policies import resolve_builtin
from policy_server_tpu.policies.wasm_oracle import oracle_wasm

from conftest import build_admission_review_dict


def pod_review(namespace: str, privileged: bool) -> ValidateRequest:
    doc = build_admission_review_dict()
    doc["request"]["namespace"] = namespace
    doc["request"]["object"] = {
        "apiVersion": "v1",
        "kind": "Pod",
        "metadata": {"name": "p", "namespace": namespace},
        "spec": {
            "containers": [
                {
                    "name": "c",
                    "image": "nginx",
                    "securityContext": {"privileged": privileged},
                }
            ]
        },
    }
    return ValidateRequest.from_admission(
        AdmissionReviewRequest.from_dict(doc).request
    )


@pytest.fixture(scope="module")
def mixed_group_env(tmp_path_factory):
    """Group 'guard' = wasmpriv() && ns(): a REAL wasm member (the
    WAT-authored pod-privileged oracle over the waPC protocol) AND'd with
    an IR member."""
    wasm_path = tmp_path_factory.mktemp("wasm") / "priv.wasm"
    wasm_path.write_bytes(oracle_wasm("pod-privileged"))
    wasm_module = load_artifact(wasm_path)

    def resolver(url: str):
        if url.endswith(".wasm"):
            return wasm_module
        builtin = resolve_builtin(url)
        assert builtin is not None, url
        return builtin

    def build(backend: str):
        return EvaluationEnvironmentBuilder(
            backend=backend, module_resolver=resolver
        ).build(
            {
                "guard": parse_policy_entry(
                    "guard",
                    {
                        "expression": "wasmpriv() && ns()",
                        "message": "pod guard rejected",
                        "policies": {
                            "wasmpriv": {"module": "file:///priv.wasm"},
                            "ns": {
                                "module": "builtin://namespace-validate",
                                "settings": {
                                    "denied_namespaces": ["blocked"]
                                },
                            },
                        },
                    },
                ),
            }
        )

    return build("jax"), build("oracle")


CASES = [
    # (namespace, privileged) → allowed, rejecting member (or None)
    ("default", False, True, None),
    ("default", True, False, "wasmpriv"),
    ("blocked", False, False, "ns"),
]


@pytest.mark.parametrize("namespace,privileged,want_allowed,rejecter", CASES)
def test_mixed_group_device_path(
    mixed_group_env, namespace, privileged, want_allowed, rejecter
):
    env, _ = mixed_group_env
    resp = env.validate("guard", pod_review(namespace, privileged))
    assert resp.allowed is want_allowed
    if not want_allowed:
        assert resp.status.message == "pod guard rejected"
        fields = [c.field for c in resp.status.details.causes]
        assert f"spec.policies.{rejecter}" in fields


def test_all_paths_agree(mixed_group_env):
    """Device batch (native), host fast-path, and the oracle backend must
    produce identical responses for the mixed group."""
    env, oracle_env = mixed_group_env
    items = [
        ("guard", pod_review(ns, priv))
        for ns, priv, _, _ in CASES
        for _ in range(3)
    ]
    device = env.validate_batch(items)
    fast = env.validate_batch(items, prefer_host=True)
    oracle = oracle_env.validate_batch(items)
    for d, f, o in zip(device, fast, oracle):
        assert not isinstance(d, Exception), d
        assert d.to_dict() == f.to_dict() == o.to_dict()


def test_wasm_member_cause_message_is_from_wasm(mixed_group_env):
    env, _ = mixed_group_env
    resp = env.validate("guard", pod_review("default", True))
    (cause,) = [
        c
        for c in resp.status.details.causes
        if c.field == "spec.policies.wasmpriv"
    ]
    # the message is the wasm guest's own rejection message
    assert "wasm oracle policy" in cause.message


def test_unreferenced_wasm_member_never_evaluated(tmp_path):
    """Masked evaluated-semantics hold for wasm members: a member the
    expression never references produces no cause."""
    wasm_path = tmp_path / "priv.wasm"
    wasm_path.write_bytes(oracle_wasm("pod-privileged"))
    wasm_module = load_artifact(wasm_path)

    def resolver(url: str):
        if url.endswith(".wasm"):
            return wasm_module
        return resolve_builtin(url)

    env = EvaluationEnvironmentBuilder(
        backend="jax", module_resolver=resolver
    ).build(
        {
            "g": parse_policy_entry(
                "g",
                {
                    # wasmpriv defined but NOT referenced
                    "expression": "ns()",
                    "message": "denied",
                    "policies": {
                        "wasmpriv": {"module": "file:///priv.wasm"},
                        "ns": {
                            "module": "builtin://namespace-validate",
                            "settings": {"denied_namespaces": ["blocked"]},
                        },
                    },
                },
            )
        }
    )
    resp = env.validate("g", pod_review("blocked", True))
    assert resp.allowed is False
    fields = [c.field for c in resp.status.details.causes]
    assert fields == ["spec.policies.ns"]


def test_mutating_wasm_member_rejects_group():
    """A wasm member whose verdict carries a mutation rejects the whole
    group with the reference's message (integration_test.rs:239-251)."""
    from policy_server_tpu.evaluation.environment import (
        GROUP_MUTATION_MESSAGE,
    )
    from policy_server_tpu.ops.compiler import PolicyProgram, Rule
    from policy_server_tpu.ops.ir import false
    from policy_server_tpu.policies.base import SettingsValidationResponse

    class MutatingWasmStub:
        name = "mutator"
        digest = "stub"

        def build(self, settings):
            return PolicyProgram(
                rules=(Rule("wasm-host-executed", false(), "unreachable"),),
                host_evaluator=lambda payload: {
                    "accepted": True,
                    "mutated_object": {"patched": True},
                },
            )

        def validate_settings(self, settings):
            return SettingsValidationResponse(valid=True, message=None)

    def resolver(url: str):
        if url == "stub://mutator":
            return MutatingWasmStub()
        return resolve_builtin(url)

    env = EvaluationEnvironmentBuilder(
        backend="jax", module_resolver=resolver
    ).build(
        {
            "g": parse_policy_entry(
                "g",
                {
                    "expression": "mut() || happy()",
                    "message": "denied",
                    "policies": {
                        "mut": {"module": "stub://mutator"},
                        "happy": {"module": "builtin://always-happy"},
                    },
                },
            )
        }
    )
    resp = env.validate("g", pod_review("default", False))
    assert resp.allowed is False
    assert resp.status.message == GROUP_MUTATION_MESSAGE
    assert resp.status.code == 500
    # the fast-path agrees
    (fast,) = env.validate_batch(
        [("g", pod_review("default", False))], prefer_host=True
    )
    assert fast.to_dict() == resp.to_dict()


def test_wasm_member_through_batcher(mixed_group_env):
    """Serving path: the mixed group batches through the MicroBatcher on
    the device path (threshold 0 forces device)."""
    from policy_server_tpu.api.service import RequestOrigin
    from policy_server_tpu.runtime.batcher import MicroBatcher
    from policy_server_tpu.telemetry import metrics as metrics_mod

    metrics_mod.reset_metrics_for_tests()
    env, _ = mixed_group_env
    b = MicroBatcher(
        env, host_fastpath_threshold=0, max_batch_size=8, batch_timeout_ms=5.0
    ).start()
    try:
        ok = b.evaluate("guard", pod_review("default", False), RequestOrigin.VALIDATE)
        assert ok.allowed is True
        bad = b.evaluate("guard", pod_review("default", True), RequestOrigin.VALIDATE)
        assert bad.allowed is False
    finally:
        b.shutdown()
        metrics_mod.reset_metrics_for_tests()
