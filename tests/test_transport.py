"""Wire-format transport contract (ops/codec.py to_transport + the
device-side unpack): the bit-packed / uint16-narrowed transport forms
must decode to EXACTLY the features the wide form decodes to, the narrow
form must refuse vocabularies that no longer fit uint16, and width-keyed
dispatch must stay unambiguous across every form of every schema."""

from __future__ import annotations

import numpy as np
import pytest

from policy_server_tpu.evaluation.environment import EvaluationEnvironmentBuilder
from policy_server_tpu.models import AdmissionReviewRequest, ValidateRequest
from policy_server_tpu.models.policy import parse_policy_entry
from policy_server_tpu.ops.codec import PACKED_KEY

from conftest import build_admission_review_dict

POLICIES = {
    "priv": {"module": "builtin://pod-privileged"},
    "ns": {
        "module": "builtin://namespace-validate",
        "settings": {"denied_namespaces": ["blocked"]},
    },
    "latest": {"module": "builtin://disallow-latest-tag"},
}


@pytest.fixture(scope="module")
def env():
    return EvaluationEnvironmentBuilder(backend="jax").build(
        {k: parse_policy_entry(k, v) for k, v in POLICIES.items()}
    )


def _encode_batch(env, docs):
    schema = env.schemas[0]
    encoded = []
    for doc in docs:
        req = ValidateRequest.from_admission(
            AdmissionReviewRequest.from_dict(doc).request
        )
        encoded.append(schema.encode(req.payload(), env.table))
    return schema, schema.pack(schema.stack(encoded, batch_size=len(docs)))


def _docs():
    out = []
    for ns, priv, image in (
        ("default", False, "r:1.2"),
        ("blocked", True, "r:latest"),
        ("x", True, ""),
    ):
        d = build_admission_review_dict()
        d["request"]["namespace"] = ns
        d["request"]["object"] = {
            "spec": {"containers": [
                {"image": image, "securityContext": {"privileged": priv}}
            ]}
        }
        out.append(d)
    return out


def test_widths_unique_across_all_forms(env):
    widths = []
    for s in env.schemas:
        lo = s.packed_layout()
        widths += [lo.width, lo.transport_width, lo.transport16_width]
    assert len(widths) == len(set(widths))


def test_narrow_and_t8_decode_identically_to_wide(env):
    schema, wide = _encode_batch(env, _docs())
    t8 = schema.to_transport(wide, vocab_size=None)
    t16 = schema.to_transport(wide, vocab_size=len(env.table))
    lo = schema.packed_layout()
    assert t8[PACKED_KEY].shape[1] == lo.transport_width
    assert t16[PACKED_KEY].shape[1] == lo.transport16_width
    ref = {k: np.asarray(v) for k, v in env._unpack_features(wide).items()}
    for label, form in (("t8", t8), ("t16", t16)):
        got = {k: np.asarray(v) for k, v in env._unpack_features(form).items()}
        assert set(got) == set(ref), label
        for k in ref:
            np.testing.assert_array_equal(got[k], ref[k], err_msg=f"{label}:{k}")


def test_vocab_overflow_falls_back_to_int32_transport(env):
    schema, wide = _encode_batch(env, _docs())
    lo = schema.packed_layout()
    over = schema.to_transport(wide, vocab_size=65537)
    assert over[PACKED_KEY].shape[1] == lo.transport_width  # not narrow
    ref = {k: np.asarray(v) for k, v in env._unpack_features(wide).items()}
    got = {k: np.asarray(v) for k, v in env._unpack_features(over).items()}
    for k in ref:
        np.testing.assert_array_equal(got[k], ref[k])


def test_to_transport_idempotent(env):
    schema, wide = _encode_batch(env, _docs())
    t16 = schema.to_transport(wide, vocab_size=len(env.table))
    again = schema.to_transport(t16, vocab_size=len(env.table))
    np.testing.assert_array_equal(again[PACKED_KEY], t16[PACKED_KEY])


def test_verdicts_identical_through_run_batch(env):
    """End to end through run_batch (which converts to transport): the
    verdicts match a direct per-key evaluation of the same rows."""
    docs = _docs()
    reqs = [
        ValidateRequest.from_admission(
            AdmissionReviewRequest.from_dict(d).request
        )
        for d in docs
    ]
    for pid, wants in (
        ("priv", [True, False, False]),
        ("ns", [True, False, True]),
        ("latest", [True, False, False]),
    ):
        for r, want in zip(reqs, wants):
            resp = env.validate(pid, r)
            assert resp.allowed is want, (pid, r.payload(), resp.to_dict())
