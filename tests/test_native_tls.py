"""Native TLS termination tests (csrc/httpfront.cpp memory-BIO
handshakes + runtime/native_frontend.NativeTlsManager + certs.py).

The core mirrors test_native_frontend.py's differential framing corpus,
now TLS-terminated: the same byte streams — valid, malformed,
pipelined, keep-alive, oversized — replayed through ssl-wrapped sockets
against two live HTTPS servers that differ ONLY in which frontend
terminates the handshake; status lines, headers, and body bytes must
match exactly (Date is the one excluded volatile). mTLS client-CA
verification must reject wrong-CA and cert-less clients at the
handshake on BOTH terminators, and accept the good client with
byte-exact verdicts.

The hardening corpus drives the abuse surfaces round 13 gave the
plaintext parser, one layer down: the handshake-arrival timeout (byte
drips never refresh it — a TLS-layer slowloris is reaped on schedule),
mid-handshake disconnect reaping, the connection cap answering its
in-band 503 close_notify-CLEAN (read to EOF with
``suppress_ragged_eofs=False``), and the loud aiohttp-TLS fallback when
libssl is unavailable.

Certificates come from tools/tlsgen.py (openssl CLI only — the
container has no ``cryptography`` package, by design)."""

from __future__ import annotations

import json
import socket
import ssl
import time

import pytest
import requests

from test_server import ServerHandle, make_config, pod_review_body
from test_native_frontend import (
    normalize,
    parse_responses,
    post_bytes,
    review,
)
from policy_server_tpu.config import TlsConfig
from tools import tlsgen

nf = pytest.importorskip(
    "policy_server_tpu.runtime.native_frontend",
    reason="native frontend module unavailable",
)

pytestmark = [
    pytest.mark.skipif(
        not nf.native_available(),
        reason="httpfront.cpp failed to build (no g++?)",
    ),
    pytest.mark.skipif(
        not tlsgen.openssl_available(),
        reason="openssl CLI unavailable — cannot mint test certificates",
    ),
    pytest.mark.skipif(
        nf.native_available() and not nf.tls_available(),
        reason="libssl unavailable — native TLS degrades to the aiohttp "
        "terminator, covered by test_fallback_when_libssl_unavailable",
    ),
]


# -- certificate material ----------------------------------------------------


@pytest.fixture(scope="module")
def certs(tmp_path_factory):
    d = tmp_path_factory.mktemp("tlsmat")
    cert, key = tlsgen.self_signed_identity(d)
    ca_cert, ca_key = tlsgen.make_ca(d)
    good_cert, good_key = tlsgen.issue_cert(
        d, ca_cert, ca_key, cn="good-client"
    )
    wrong_ca_cert, wrong_ca_key = tlsgen.make_ca(
        d, cn="wrong-ca", stem="wrongca"
    )
    bad_cert, bad_key = tlsgen.issue_cert(
        d, wrong_ca_cert, wrong_ca_key, cn="bad-client", stem="badclient"
    )
    return {
        "dir": d,
        "cert": str(cert), "key": str(key),
        "ca": str(ca_cert),
        "good_cert": str(good_cert), "good_key": str(good_key),
        "bad_cert": str(bad_cert), "bad_key": str(bad_key),
    }


def client_ctx(certfile=None, keyfile=None) -> ssl.SSLContext:
    ctx = ssl.create_default_context()
    ctx.check_hostname = False
    ctx.verify_mode = ssl.CERT_NONE
    if certfile:
        ctx.load_cert_chain(certfile, keyfile)
    return ctx


def send_raw_tls(
    port: int,
    data: bytes,
    *,
    ctx: ssl.SSLContext | None = None,
    timeout: float = 15.0,
) -> bytes:
    s = (ctx or client_ctx()).wrap_socket(
        socket.create_connection(("127.0.0.1", port))
    )
    try:
        s.sendall(data)
        s.settimeout(timeout)
        out = b""
        try:
            while True:
                chunk = s.recv(65536)
                if not chunk:
                    break
                out += chunk
        except socket.timeout:
            pass
        return out
    finally:
        s.close()


def assert_identical_tls(
    pair, payload: bytes, n_responses: int | None = None, *, ctx=None
):
    py, nat = pair
    a = normalize(
        parse_responses(send_raw_tls(py.server.api_port, payload, ctx=ctx))
    )
    b = normalize(
        parse_responses(send_raw_tls(nat.server.api_port, payload, ctx=ctx))
    )
    assert a == b, (
        f"TLS frontends diverged for {payload[:120]!r}...\n"
        f"python: {a}\nnative: {b}"
    )
    if n_responses is not None:
        assert len(a) == n_responses
    return a


# -- server pairs ------------------------------------------------------------


@pytest.fixture(scope="module")
def tls_pair(certs):
    """One policy set, two TLS terminators: (python, native)."""
    from policy_server_tpu.telemetry import metrics as metrics_mod

    metrics_mod.reset_metrics_for_tests()
    tls = TlsConfig(cert_file=certs["cert"], key_file=certs["key"])
    py = ServerHandle(make_config(frontend="python", tls_config=tls))
    nat = ServerHandle(make_config(frontend="native", tls_config=tls))
    assert nat.server._native_frontend is not None
    assert nat.server._native_tls is not None, (
        "TLS did not terminate natively despite tls_available()"
    )
    yield py, nat
    nat.stop()
    py.stop()


@pytest.fixture(scope="module")
def mtls_pair(certs):
    """The same pair with client-CA verification required."""
    from policy_server_tpu.telemetry import metrics as metrics_mod

    metrics_mod.reset_metrics_for_tests()
    tls = TlsConfig(
        cert_file=certs["cert"], key_file=certs["key"],
        client_ca_file=(certs["ca"],),
    )
    py = ServerHandle(make_config(frontend="python", tls_config=tls))
    nat = ServerHandle(make_config(frontend="native", tls_config=tls))
    assert nat.server._native_tls is not None
    yield py, nat
    nat.stop()
    py.stop()


# -- the TLS differential corpus ---------------------------------------------


def test_valid_verdicts_bit_exact_over_tls(tls_pair):
    for privileged in (True, False):
        body = json.dumps(pod_review_body(privileged)).encode()
        resps = assert_identical_tls(
            tls_pair, post_bytes("/validate/pod-privileged", body), 1
        )
        assert resps[0][0] == "HTTP/1.1 200 OK"
        verdict = json.loads(resps[0][2])
        assert verdict["response"]["allowed"] is (not privileged)


def test_keep_alive_and_pipelining_over_tls(tls_pair):
    body = review()
    wire = (
        post_bytes("/validate/pod-privileged", body, close=False)
        + post_bytes("/validate/pod-privileged-monitor", body, close=False)
        + post_bytes("/validate/pod-privileged", body, close=True)
    )
    resps = assert_identical_tls(tls_pair, wire, 3)
    assert all(s == "HTTP/1.1 200 OK" for s, _h, _b in resps)


def test_malformed_bodies_over_tls(tls_pair):
    for wire in (
        post_bytes("/validate/pod-privileged", b"{not json"),
        post_bytes("/validate/pod-privileged", b'{"no": "review"}'),
    ):
        assert_identical_tls(tls_pair, wire)
    # framing garbage: status parity only, like the plaintext corpus
    # (aiohttp embeds the offending bytes in its 400 body)
    for handle in tls_pair:
        out = send_raw_tls(handle.server.api_port, b"BLARGH\r\n\r\n")
        assert b" 400 " in out.split(b"\r\n", 1)[0], out[:100]


def test_oversized_body_over_tls(tls_pair):
    """413 parity through the TLS pipe, modulo aiohttp's
    transport-chunking byte count (same mask as the plaintext
    corpus)."""
    import re

    def mask(resps):
        return [
            (s, h, re.sub(rb"actual body size \d+", b"actual body size N", b))
            for s, h, b in resps
        ]

    py, nat = tls_pair
    big = review(obj={"filler": "x" * (9 * 1024 * 1024)})
    wire = post_bytes("/validate/pod-privileged", big)
    a = normalize(parse_responses(send_raw_tls(py.server.api_port, wire)))
    b = normalize(parse_responses(send_raw_tls(nat.server.api_port, wire)))
    for resps in (a, b):
        for _s, h, _b in resps:
            h.pop("content-length", None)
    assert mask(a) == mask(b), f"python: {a}\nnative: {b}"
    assert a[0][0] == "HTTP/1.1 413 Request Entity Too Large"


def test_mtls_rejects_and_accepts_at_parity(mtls_pair, certs):
    """Client-CA verification parity: a wrong-CA client and a cert-less
    client FAIL THE HANDSHAKE on both terminators (CPython's
    CERT_REQUIRED semantics — no HTTP-layer 403 exists on this path);
    the good client gets byte-exact verdicts."""
    py, nat = mtls_pair

    def rejected(handle, ctx) -> bool:
        """True when the server refuses to serve HTTP: the alert may
        surface as SSLError (native sends certificate_required /
        unknown_ca) or as a bare close (asyncio's transport drops the
        connection) — both are handshake rejections, neither is a
        response."""
        try:
            s = ctx.wrap_socket(
                socket.create_connection(
                    ("127.0.0.1", handle.server.api_port)
                )
            )
            s.settimeout(5)
            s.sendall(b"GET / HTTP/1.1\r\nHost: t\r\n\r\n")
            data = s.recv(1000)
            s.close()
            return data == b""
        except (OSError, ssl.SSLError):  # ConnectionError is an OSError
            return True

    for handle in (py, nat):
        assert rejected(handle, client_ctx()), "cert-less client served"
        assert rejected(
            handle, client_ctx(certs["bad_cert"], certs["bad_key"])
        ), "wrong-CA client served"
    good = client_ctx(certs["good_cert"], certs["good_key"])
    resps = assert_identical_tls(
        mtls_pair,
        post_bytes("/validate/pod-privileged", review()),
        1,
        ctx=good,
    )
    assert resps[0][0] == "HTTP/1.1 200 OK"
    nstats = nat.server._native_frontend.stats()
    assert nstats["tls_handshakes_failed"] >= 2


# -- the handshake-abuse hardening corpus (mini native frontend) -------------


class _EchoSink:
    def handle_burst(self, frontend, burst):
        for rec in burst:
            frontend.complete(rec[0], 200, b'{"ok": true}')


def _mini_tls_frontend(certs, **kw):
    sock = nf.make_listen_socket("127.0.0.1", 0)
    port = sock.getsockname()[1]
    front = nf.NativeFrontend(sock, _EchoSink(), **kw)
    handle = nf.tls_ctx_create(
        open(certs["cert"], "rb").read(), open(certs["key"], "rb").read()
    )
    front.set_tls(handle)
    front.start()
    return front, port, handle


def test_connection_cap_answers_close_notify_clean(certs):
    """The cap's in-band 503 must arrive over a COMPLETED handshake and
    end in close_notify — ``suppress_ragged_eofs=False`` turns a missing
    alert into SSLEOFError, so reading to EOF is the assertion."""
    front, port, h = _mini_tls_frontend(certs, max_connections=2)
    try:
        ctx = client_ctx()
        keep = []
        for _ in range(2):
            s = ctx.wrap_socket(socket.create_connection(("127.0.0.1", port)))
            s.sendall(post_bytes("/validate/p", b"{}", close=False))
            assert s.recv(200).startswith(b"HTTP/1.1 200")
            keep.append(s)
        over = ctx.wrap_socket(
            socket.create_connection(("127.0.0.1", port)),
            suppress_ragged_eofs=False,
        )
        over.settimeout(10)
        data = b""
        while True:  # SSLEOFError here = truncation without close_notify
            chunk = over.recv(65536)
            if not chunk:
                break
            data += chunk
        resps = parse_responses(data)
        assert resps[0][0] == "HTTP/1.1 503 Service Unavailable"
        assert resps[0][1]["retry-after"]
        st = front.stats()
        assert st["conn_cap_rejections"] == 1
        assert st["tls_clean_closes"] >= 1
        for s in keep:
            s.close()
    finally:
        front.shutdown(timeout=5)
        nf.tls_ctx_free(h)


def test_handshake_timeout_reaps_tls_slowloris(certs):
    """A ClientHello dripping one byte at a time must be reaped when the
    ARRIVAL deadline (anchored at accept) expires — the drips themselves
    never refresh it."""
    front, port, h = _mini_tls_frontend(certs)
    front.configure_tls(handshake_timeout_ms=1000)
    try:
        s = socket.create_connection(("127.0.0.1", port))
        s.settimeout(1.0)
        t0 = time.monotonic()
        hello_prefix = b"\x16\x03\x01\x00\xc8\x01\x00\x00"
        closed_at = None
        for b in hello_prefix * 4:  # keep dripping well past the deadline
            try:
                s.sendall(bytes([b]))
                if s.recv(1) == b"":
                    closed_at = time.monotonic() - t0
                    break
            except socket.timeout:
                continue
            except OSError:
                closed_at = time.monotonic() - t0
                break
        assert closed_at is not None, "dripping handshake was never reaped"
        # reaped on the arrival deadline (1 s) + sweep cadence (1 s),
        # NOT refreshed per drip (32 drips x 1 s would be >30 s)
        assert closed_at < 10.0
        assert front.stats()["tls_handshake_timeouts"] == 1
        s.close()
    finally:
        front.shutdown(timeout=5)
        nf.tls_ctx_free(h)


def test_mid_handshake_disconnect_reaped_and_counted(certs):
    front, port, h = _mini_tls_frontend(certs)
    try:
        s = socket.create_connection(("127.0.0.1", port))
        s.sendall(b"\x16\x03\x01\x00\x80\x01\x00")  # ClientHello fragment
        s.close()
        deadline = time.time() + 5
        while (
            time.time() < deadline
            and front.stats()["tls_handshake_disconnects"] == 0
        ):
            time.sleep(0.05)
        st = front.stats()
        assert st["tls_handshake_disconnects"] == 1
        assert st["tls_connections"] == 1
    finally:
        front.shutdown(timeout=5)
        nf.tls_ctx_free(h)


# -- loud degradation ---------------------------------------------------------


def test_fallback_when_libssl_unavailable(monkeypatch, caplog, certs):
    """--frontend native + TLS with no usable libssl must fall back to
    the aiohttp TLS terminator with ONE loud warning — and bench/metrics
    must be able to tell (native_tls stays None, the termination gauge
    reads 0)."""
    import logging

    from policy_server_tpu.runtime import native_frontend as mod
    from policy_server_tpu.telemetry import metrics as metrics_mod

    metrics_mod.reset_metrics_for_tests()
    monkeypatch.setattr(mod, "tls_available", lambda: False)
    monkeypatch.setattr(
        mod, "tls_error", lambda: "libssl.so: cannot open shared object"
    )
    tls = TlsConfig(cert_file=certs["cert"], key_file=certs["key"])
    with caplog.at_level(logging.WARNING):
        handle = ServerHandle(make_config(frontend="native", tls_config=tls))
    try:
        assert handle.server._native_frontend is None
        assert handle.server._native_tls is None
        assert handle.server.state.native_tls is None
        assert any(
            "native TLS unavailable" in r.getMessage()
            and "falling back" in r.getMessage()
            for r in caplog.records
        ), "fallback was not loud"
        r = requests.post(
            f"https://127.0.0.1:{handle.server.api_port}"
            "/validate/pod-privileged",
            json=pod_review_body(True),
            verify=False,
            timeout=60,
        )
        assert r.status_code == 200
        assert r.json()["response"]["allowed"] is False
    finally:
        handle.stop()
