"""Round-17 crash tolerance: the durable last-good state store.

Covers the crash-consistency contract at every altitude:

* journal framing + atomic-write mechanics (unit);
* the corrupt-manifest FUZZ: flip/truncate the journal at byte
  granularity and assert boot always lands on a previously-persisted
  last-good state or clean cold — never a crash, never a silently
  wrong epoch;
* the artifact cache's content-address verification + quarantine;
* the audit spill/restore roundtrip;
* warm boot end to end: a server reboots with its artifact SOURCE gone
  and still serves the pinned set bit-exactly (zero fetch), and an
  UNPINNED failed fetch degrades loudly to last-good;
* tenant boot degrade through the ``tenant.reload`` failpoint;
* the supervision surface: a dead batcher dispatch loop detected and
  revived by the self-heal watchdog.
"""

from __future__ import annotations

import json
import sys
import threading
import time
from pathlib import Path

import pytest

REPO_ROOT = Path(__file__).resolve().parents[1]
if str(REPO_ROOT) not in sys.path:
    sys.path.insert(0, str(REPO_ROOT))

from policy_server_tpu import failpoints  # noqa: E402
from policy_server_tpu.statestore import (  # noqa: E402
    StateStore,
    atomic_write_bytes,
    compute_fingerprint,
    frame_records,
    parse_records,
)


# ---------------------------------------------------------------------------
# journal + atomic write mechanics
# ---------------------------------------------------------------------------


def test_atomic_write_leaves_no_temp_and_replaces(tmp_path):
    p = tmp_path / "f.bin"
    atomic_write_bytes(p, b"one")
    atomic_write_bytes(p, b"two")
    assert p.read_bytes() == b"two"
    assert [x.name for x in tmp_path.iterdir()] == ["f.bin"]


def test_journal_roundtrip_and_torn_tail():
    records = [(1, {"a": 1}), (2, {"b": "x"}), (3, {"c": [1, 2]})]
    data = frame_records(records)
    parsed, corrupt = parse_records(data)
    assert parsed == records and not corrupt
    # torn tail: drop the last 3 bytes — the valid prefix survives
    parsed, corrupt = parse_records(data[:-3])
    assert parsed == records[:2] and corrupt


def test_manifest_persist_reload_and_retention(tmp_path):
    s = StateStore(tmp_path)
    for epoch in range(5):
        s.persist_manifest(
            "default", epoch=epoch, outcome="promoted",
            policy_ids=[f"p{epoch}"], policies_yaml=f"v: {epoch}\n",
        )
    s2 = StateStore(tmp_path)
    m = s2.last_good_manifest("default")
    assert m["epoch"] == 4 and m["policy_ids"] == ["p4"]
    # retention: current + pinned-previous only (the on-disk analog of
    # the lifecycle's one-generation rollback pin)
    assert s2.stats()["journal_records"] == 2


def test_manifest_is_per_tenant(tmp_path):
    s = StateStore(tmp_path)
    s.persist_manifest("default", epoch=3, outcome="promoted",
                       policy_ids=["a"])
    s.persist_manifest("ten-1", epoch=7, outcome="boot", policy_ids=["b"])
    s2 = StateStore(tmp_path)
    assert s2.last_good_manifest("default")["epoch"] == 3
    assert s2.last_good_manifest("ten-1")["epoch"] == 7
    assert s2.last_good_manifest("ten-2") is None


# ---------------------------------------------------------------------------
# the corrupt-manifest fuzz (satellite): byte-granularity damage
# ---------------------------------------------------------------------------


def _seed_store(tmp_path) -> tuple[Path, list[tuple[int, str]]]:
    """A store with two generations persisted; returns the journal path
    and the set of VALID (epoch, policies_digest) states boot may land
    on (plus clean-cold None)."""
    s = StateStore(tmp_path)
    valid = []
    for epoch in (0, 1):
        yaml_text = f"set: {epoch}\n"
        s.persist_manifest(
            "default", epoch=epoch, outcome="promoted",
            policy_ids=[f"p{epoch}"], policies_yaml=yaml_text,
        )
        valid.append(
            (epoch, s.last_good_manifest("default")["policies_digest"])
        )
    return tmp_path / StateStore.MANIFESTS_JOURNAL, valid


def _assert_last_good_or_cold(tmp_path, valid) -> int | None:
    """Open the store over (possibly damaged) state; the outcome must be
    a previously-persisted generation or clean cold — never an
    exception, never a manifest that was never persisted."""
    s = StateStore(tmp_path)  # must not raise, whatever the damage
    m = s.last_good_manifest("default")
    if m is None:
        return None
    assert (m["epoch"], m["policies_digest"]) in valid, (
        f"silently wrong epoch after damage: {m}"
    )
    return m["epoch"]


def test_fuzz_manifest_byte_flips(tmp_path):
    journal, valid = _seed_store(tmp_path)
    pristine = journal.read_bytes()
    outcomes = {0: 0, 1: 0, None: 0}
    for pos in range(len(pristine)):
        damaged = bytearray(pristine)
        damaged[pos] ^= 0xFF
        journal.write_bytes(bytes(damaged))
        outcomes[_assert_last_good_or_cold(tmp_path, valid)] += 1
        # reset for the next position (fsck may have quarantined it)
        journal.write_bytes(pristine)
    # the damage landed everywhere, so every recovery class must have
    # been exercised: flips in record 1 keep epoch 0, flips in record 0
    # lose everything (clean cold), and SOME flips (e.g. inside the
    # yaml text of a record whose crc then fails) never yield epoch 1
    assert outcomes[0] > 0 and outcomes[None] > 0
    # a flipped byte can never fabricate a passing record, so epoch 1
    # only survives when the flip landed... nowhere: every byte of a
    # 2-record journal is covered by a crc, so epoch-1 survivals are 0
    assert outcomes[1] == 0


def test_fuzz_manifest_truncations(tmp_path):
    journal, valid = _seed_store(tmp_path)
    pristine = journal.read_bytes()
    saw_cold = saw_prefix = False
    for cut in range(len(pristine)):
        journal.write_bytes(pristine[:cut])
        epoch = _assert_last_good_or_cold(tmp_path, valid)
        saw_cold |= epoch is None
        saw_prefix |= epoch == 0
        journal.write_bytes(pristine)
    assert saw_cold and saw_prefix
    # untouched journal still loads the newest generation
    assert _assert_last_good_or_cold(tmp_path, valid) == 1


def test_fsck_quarantines_and_salvages(tmp_path):
    journal, valid = _seed_store(tmp_path)
    data = bytearray(journal.read_bytes())
    data[-10] ^= 0x01  # corrupt the LAST record only
    journal.write_bytes(bytes(data))
    s = StateStore(tmp_path)
    assert s.last_good_manifest("default")["epoch"] == 0
    assert s.stats()["fsck_quarantined"] == 1
    q = list((tmp_path / StateStore.QUARANTINE_DIR).iterdir())
    assert len(q) == 1 and "manifests.journal" in q[0].name
    # the salvage was rewritten clean: a THIRD open quarantines nothing
    assert StateStore(tmp_path).stats()["fsck_quarantined"] == 0


def test_stray_tmp_files_are_swept(tmp_path):
    StateStore(tmp_path)  # layout
    (tmp_path / "manifests.journal.tmp.1234").write_bytes(b"torn")
    s = StateStore(tmp_path)
    assert s.stats()["fsck_quarantined"] == 1
    assert not (tmp_path / "manifests.journal.tmp.1234").exists()


# ---------------------------------------------------------------------------
# artifact cache
# ---------------------------------------------------------------------------


def test_artifact_cache_roundtrip_and_pinning(tmp_path):
    s = StateStore(tmp_path)
    d = s.record_artifact("http://r/p.tpp.json", b"bundle-bytes")
    path = s.cached_artifact("http://r/p.tpp.json")
    assert path.read_bytes() == b"bundle-bytes"
    s.persist_manifest(
        "default", epoch=0, outcome="boot", policy_ids=["p"],
        policies_yaml="p: 1\n",
        artifact_digests={"http://r/p.tpp.json": d},
    )
    s2 = StateStore(tmp_path)
    assert s2.pinned_digests("default", "p: 1\n") == {
        "http://r/p.tpp.json": d
    }
    # a CHANGED config pins nothing (live fetch preferred)
    assert s2.pinned_digests("default", "p: 2\n") == {}
    assert s2.pinned_digests("default", None) == {}


def test_artifact_bitflip_quarantined_never_loads(tmp_path):
    s = StateStore(tmp_path)
    d = s.record_artifact("http://r/p.tpp.json", b"bundle-bytes")
    blob = tmp_path / StateStore.ARTIFACTS_DIR / d
    data = bytearray(blob.read_bytes())
    data[0] ^= 0xFF
    blob.write_bytes(bytes(data))
    # read path: verification fails, blob quarantined, miss returned
    s2 = StateStore(tmp_path)  # fsck already catches it at open
    assert s2.cached_artifact("http://r/p.tpp.json") is None
    assert s2.stats()["fsck_quarantined"] >= 1


# ---------------------------------------------------------------------------
# audit spill
# ---------------------------------------------------------------------------


def test_audit_spill_roundtrip_with_snapshot_store(tmp_path):
    from policy_server_tpu.audit.snapshot import (
        SnapshotStore,
        synthesize_review,
    )

    store = SnapshotStore()
    reviews = [
        synthesize_review(
            {"apiVersion": "v1", "kind": "Pod",
             "metadata": {"name": f"p{i}", "namespace": "ns"}},
            "CREATE", uid=f"u{i}",
        )
        for i in range(5)
    ]
    store.observe(reviews)
    s = StateStore(tmp_path)
    n = s.spill_audit(
        {"v1/Pod": "1234"},
        {"v1/Pod": {("uid", "u0"): "/v1/Pod/ns/p0"}},
        store.export_rows(),
    )
    assert n == 5
    loaded = StateStore(tmp_path).load_audit_spill()
    assert loaded["rvs"] == {"v1/Pod": "1234"}
    assert loaded["fed"]["v1/Pod"] == {("uid", "u0"): "/v1/Pod/ns/p0"}
    restored = SnapshotStore()
    assert restored.restore_rows(loaded["rows"]) == 5
    assert sorted(k for k, _ in restored.export_rows()) == sorted(
        k for k, _ in store.export_rows()
    )
    # payloads byte-identical: re-scans after a restart are cache hits
    assert dict(restored.export_rows()) == dict(store.export_rows())


def test_audit_spill_torn_tail_keeps_prefix(tmp_path):
    s = StateStore(tmp_path)
    s.spill_audit({"v1/Pod": "9"}, {}, [
        (f"k{i}", json.dumps({"i": i}).encode()) for i in range(4)
    ])
    spill = tmp_path / StateStore.AUDIT_SPILL
    data = spill.read_bytes()
    spill.write_bytes(data[:-5])
    loaded = StateStore(tmp_path).load_audit_spill()
    assert loaded is not None and loaded["rvs"] == {"v1/Pod": "9"}
    assert len(loaded["rows"]) == 3  # the torn last row is gone, loudly


def test_fingerprint_is_stable_and_sensitive():
    a = compute_fingerprint({"ids": ["a", "b"], "kernel": "xla"})
    assert a == compute_fingerprint({"kernel": "xla", "ids": ["a", "b"]})
    assert a != compute_fingerprint({"ids": ["a"], "kernel": "xla"})


# ---------------------------------------------------------------------------
# warm boot end to end
# ---------------------------------------------------------------------------


def _write_artifact_policy(tmp_path: Path) -> Path:
    from policy_server_tpu.fetch import dump_artifact
    from policy_server_tpu.ops import ir
    from policy_server_tpu.ops.compiler import Rule
    from policy_server_tpu.ops.ir import Path as IRPath

    src = tmp_path / "deny-ns.tpp.json"
    src.write_text(json.dumps(dump_artifact(
        "deny-ns",
        [Rule("denied", ir.in_set(IRPath("namespace"), ["blocked"]),
              "namespace blocked")],
    )))
    return src


def _drill_config(tmp_path: Path, policies_path: Path):
    from policy_server_tpu.config.config import (
        Config,
        TlsConfig,
        read_policies_file,
    )

    return Config(
        addr="127.0.0.1", port=0, readiness_probe_port=0,
        tls_config=TlsConfig(),
        policies=read_policies_file(policies_path),
        policies_path=str(policies_path),
        policies_download_dir=str(tmp_path / "dl"),
        state_dir=str(tmp_path / "state"),
        policy_timeout_seconds=2.0, max_batch_size=8,
        selfheal_interval_seconds=0.0,
    )


def _validate(server, policy_id: str, namespace: str):
    from policy_server_tpu.models import (
        AdmissionRequest,
        GroupVersionKind,
        ValidateRequest,
    )

    req = ValidateRequest.from_admission(AdmissionRequest(
        uid="t", kind=GroupVersionKind(group="", version="v1", kind="Pod"),
        name="p", namespace=namespace, operation="CREATE",
        user_info={"username": "t"},
        object={"apiVersion": "v1", "kind": "Pod",
                "metadata": {"name": "p", "namespace": namespace},
                "spec": {"containers": [{"name": "c", "image": "nginx"}]}},
    ))
    [resp] = server.state.batcher.env.validate_batch(
        [(policy_id, req)], run_hooks=False
    )
    return resp


def test_warm_boot_serves_pinned_artifacts_with_source_gone(tmp_path):
    """The tentpole acceptance in-process: boot 1 fetches a file://
    artifact and caches it; boot 2 runs with the source DELETED and the
    registry failpoint armed — the pinned cache must serve, zero
    fetches, bit-exact verdicts."""
    from policy_server_tpu.server import PolicyServer

    src = _write_artifact_policy(tmp_path)
    policies_path = tmp_path / "policies.yml"
    policies_path.write_text(
        f"deny-ns:\n  module: file://{src}\n"
        "priv:\n  module: builtin://pod-privileged\n"
    )
    cfg = _drill_config(tmp_path, policies_path)
    s1 = PolicyServer.new_from_config(cfg)
    try:
        assert s1.state.boot_report["warm"] is False
        r_block = _validate(s1, "deny-ns", "blocked")
        r_ok = _validate(s1, "deny-ns", "default")
        assert not r_block.allowed and r_ok.allowed
    finally:
        s1.lifecycle.shutdown()

    src.unlink()  # the "registry" is gone
    with failpoints.active(
        "fetch.http", lambda: (_ for _ in ()).throw(
            failpoints.FailpointError("registry outage")
        )
    ):
        cfg2 = _drill_config(tmp_path, policies_path)
        s2 = PolicyServer.new_from_config(cfg2)
    try:
        report = s2.state.boot_report
        assert report["warm"] is True
        assert report["artifacts_from_cache"] == 1
        assert report["degraded_sources"] == 0
        assert report["fingerprint_match"] is True
        r_block = _validate(s2, "deny-ns", "blocked")
        r_ok = _validate(s2, "deny-ns", "default")
        assert not r_block.allowed and r_ok.allowed
        assert r_block.status.message == "namespace blocked"
    finally:
        s2.lifecycle.shutdown()


def test_changed_config_degrades_loudly_to_last_good_on_fetch_failure(
    tmp_path,
):
    """An UNPINNED url (the config changed since last-good) prefers the
    live fetch; when that fails, boot degrades LOUDLY to the newest
    cached artifact instead of fail-closing."""
    from policy_server_tpu.server import PolicyServer

    src = _write_artifact_policy(tmp_path)
    policies_path = tmp_path / "policies.yml"
    policies_path.write_text(f"deny-ns:\n  module: file://{src}\n")
    cfg = _drill_config(tmp_path, policies_path)
    s1 = PolicyServer.new_from_config(cfg)
    s1.lifecycle.shutdown()

    # change the CONFIG (new policy id) so the old manifest pins nothing,
    # and kill the source: the fetch fails, the url's cached bytes serve
    policies_path.write_text(
        f"deny-ns:\n  module: file://{src}\n"
        "extra:\n  module: builtin://always-happy\n"
    )
    src.unlink()
    cfg2 = _drill_config(tmp_path, policies_path)
    s2 = PolicyServer.new_from_config(cfg2)
    try:
        report = s2.state.boot_report
        assert report["degraded_sources"] == 1
        assert not _validate(s2, "deny-ns", "blocked").allowed
    finally:
        s2.lifecycle.shutdown()


def test_manifest_tracks_promotions_and_rollbacks(tmp_path):
    """The rollback pin survives: promote a reload, roll it back, and
    the store's last-good must follow each transition."""
    from policy_server_tpu.server import PolicyServer

    policies_path = tmp_path / "policies.yml"
    policies_path.write_text("priv:\n  module: builtin://pod-privileged\n")
    cfg = _drill_config(tmp_path, policies_path)
    srv = PolicyServer.new_from_config(cfg)
    try:
        store = srv.state.statestore
        assert store.last_good_manifest()["outcome"] == "boot"
        from policy_server_tpu.models.policy import parse_policy_entry

        # programmatic candidate set (no policies.yml rewrite: the digest
        # watcher must not race this test's explicit transitions)
        srv.lifecycle.reload(policies={
            "priv": parse_policy_entry(
                "priv", {"module": "builtin://pod-privileged"}
            ),
            "happy": parse_policy_entry(
                "happy", {"module": "builtin://always-happy"}
            ),
        }, reason="test")
        m = store.last_good_manifest()
        assert m["outcome"] == "promoted" and m["epoch"] == 1
        assert "happy" in m["policy_ids"]
        srv.lifecycle.rollback()
        m = store.last_good_manifest()
        assert m["outcome"] == "rolled-back" and m["epoch"] == 0
        # a fresh store (the next boot) sees the rolled-back pin
        assert StateStore(
            tmp_path / "state"
        ).last_good_manifest()["epoch"] == 0
    finally:
        srv.lifecycle.shutdown()


def test_tenant_boot_degrades_to_last_good_manifest(tmp_path):
    """Satellite proof for the ``tenant.reload`` failpoint at BOOT: a
    tenant whose policies file is unreadable boots DEGRADED on its
    last-good manifest; the other tenants are untouched."""
    from policy_server_tpu.server import PolicyServer
    from policy_server_tpu.tenancy import read_tenants_file

    t_policies = tmp_path / "tenant-policies.yml"
    t_policies.write_text("tpriv:\n  module: builtin://pod-privileged\n")
    tenants_yml = tmp_path / "tenants.yml"
    tenants_yml.write_text(
        "tenants:\n  ten-a:\n    policies: tenant-policies.yml\n"
    )
    policies_path = tmp_path / "policies.yml"
    policies_path.write_text("priv:\n  module: builtin://pod-privileged\n")

    def cfg():
        c = _drill_config(tmp_path, policies_path)
        c.tenants_path = str(tenants_yml)
        c.tenants = read_tenants_file(tenants_yml)
        return c

    srv = PolicyServer.new_from_config(cfg())
    srv.state.tenants.shutdown()
    srv.lifecycle.shutdown()
    assert StateStore(
        tmp_path / "state"
    ).last_good_manifest("ten-a") is not None

    def boom():
        raise failpoints.FailpointError("tenant manifest unreadable")

    with failpoints.active("tenant.reload", boom):
        srv2 = PolicyServer.new_from_config(cfg())
    try:
        ten = srv2.state.tenants.get("ten-a")
        assert "tpriv" in ten.state.evaluation_environment.policy_ids()
        assert srv2.state.boot_report["degraded_sources"] == 1
        code, _body = ten.readiness()
        assert code == 200
    finally:
        srv2.state.tenants.shutdown()
        srv2.lifecycle.shutdown()


# ---------------------------------------------------------------------------
# supervision: respawn stats + the self-heal watchdog
# ---------------------------------------------------------------------------


def test_supervisor_stats_counters():
    from policy_server_tpu.supervision import SupervisorStats

    s = SupervisorStats()
    s.count_respawn(1.5)
    s.count_respawn(0.0)
    s.count_slot_given_up()
    s.count_batcher_revive()
    s.count_frontend_revive()
    st = s.stats()
    assert st["worker_respawns"] == 2
    assert st["worker_backoff_seconds"] == 1.5
    assert st["worker_slots_given_up"] == 1
    assert st["batcher_revives"] == 1
    assert st["frontend_revives"] == 1


def test_selfheal_watchdog_revives_dead_dispatch_loop(tmp_path):
    """A batcher whose dispatch loop DIED (zombie server: submissions
    enqueue, nothing forms) is detected and rebuilt by the watchdog, and
    serving resumes."""
    from policy_server_tpu.api.state import ApiServerState
    from policy_server_tpu.evaluation.environment import (
        EvaluationEnvironmentBuilder,
    )
    from policy_server_tpu.models.policy import parse_policy_entry
    from policy_server_tpu.runtime.batcher import MicroBatcher
    from policy_server_tpu.supervision import (
        SelfHealWatchdog,
        SupervisorStats,
    )

    env = EvaluationEnvironmentBuilder(backend="oracle").build({
        "priv": parse_policy_entry(
            "priv", {"module": "builtin://pod-privileged"}
        )
    })
    batcher = MicroBatcher(env, max_batch_size=4, batch_timeout_ms=1.0)
    batcher.start()
    try:
        state = ApiServerState(evaluation_environment=env, batcher=batcher)
        stats = SupervisorStats()
        dog = SelfHealWatchdog(state, stats, interval_seconds=0.05)
        assert dog.check_once() == 0  # healthy: nothing to revive

        # kill the dispatch loop the way a real wedge would: an
        # exception escaping the loop body
        orig = batcher._maybe_dispatch_audit
        batcher._maybe_dispatch_audit = lambda: (_ for _ in ()).throw(
            RuntimeError("injected loop death")
        )
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline and batcher._thread.is_alive():
            time.sleep(0.02)
        assert not batcher._thread.is_alive(), "loop did not die"
        batcher._maybe_dispatch_audit = orig
        assert batcher.dispatch_wedged()

        dog.start()
        try:
            deadline = time.monotonic() + 5
            while (
                time.monotonic() < deadline
                and stats.stats()["batcher_revives"] == 0
            ):
                time.sleep(0.02)
            assert stats.stats()["batcher_revives"] == 1
            assert not batcher.dispatch_wedged()
            # serving resumed: a submitted request is answered
            from policy_server_tpu.models import (
                AdmissionRequest,
                GroupVersionKind,
                ValidateRequest,
            )

            req = ValidateRequest.from_admission(AdmissionRequest(
                uid="z",
                kind=GroupVersionKind(group="", version="v1", kind="Pod"),
                name="p", namespace="default", operation="CREATE",
                user_info={"username": "t"},
                object={"apiVersion": "v1", "kind": "Pod",
                        "metadata": {"name": "p"},
                        "spec": {"containers": [
                            {"name": "c", "image": "nginx"}]}},
            ))
            from policy_server_tpu.api import service as api_service

            fut = batcher.submit(
                "priv", req, api_service.RequestOrigin.VALIDATE
            )
            assert fut.result(timeout=10).allowed
        finally:
            dog.stop()
    finally:
        batcher.shutdown()
        env.close()


def test_selfheal_watchdog_never_revives_during_shutdown():
    """The wedge test must not race teardown: a batcher mid-shutdown is
    NOT wedged (its loop exiting is the intended state)."""
    from policy_server_tpu.evaluation.environment import (
        EvaluationEnvironmentBuilder,
    )
    from policy_server_tpu.models.policy import parse_policy_entry
    from policy_server_tpu.runtime.batcher import MicroBatcher

    env = EvaluationEnvironmentBuilder(backend="oracle").build({
        "priv": parse_policy_entry(
            "priv", {"module": "builtin://pod-privileged"}
        )
    })
    batcher = MicroBatcher(env, max_batch_size=4, batch_timeout_ms=1.0)
    batcher.start()
    batcher.shutdown()
    assert not batcher.dispatch_wedged()
    assert not batcher.revive_dispatch()
    env.close()


def test_artifact_sidecar_travels_into_the_cache(tmp_path):
    """A detached-signature sidecar cached alongside its artifact lands
    at <blob>.sig.json — exactly where verify_artifact looks — so a
    cache-served artifact verifies like a live-fetched one, and fsck
    never quarantines the (non-content-addressed) sidecar."""
    s = StateStore(tmp_path)
    d = s.record_artifact(
        "http://r/p.tpp.json", b"bundle-bytes",
        sidecar=b'{"signatures": []}',
    )
    blob = s.cached_artifact("http://r/p.tpp.json")
    sidecar = blob.with_name(blob.name + ".sig.json")
    assert sidecar.read_bytes() == b'{"signatures": []}'
    s2 = StateStore(tmp_path)  # fsck pass
    assert s2.stats()["fsck_quarantined"] == 0
    assert s2.stats()["artifacts_resident"] == 1  # sidecar not counted
    assert s2.cached_artifact("http://r/p.tpp.json") == blob
    assert d in blob.name


def test_pinned_digest_survives_lost_urlmap(tmp_path):
    """Regression: the manifest's digest pin is authoritative — a
    pinned artifact must load even when the url-map journal was lost to
    quarantine (that damage scenario is exactly what the pin is for)."""
    s = StateStore(tmp_path)
    d = s.record_artifact("http://r/p.tpp.json", b"bundle-bytes")
    (tmp_path / StateStore.URLMAP_JOURNAL).unlink()
    s2 = StateStore(tmp_path)
    assert s2.cached_artifact("http://r/p.tpp.json") is None  # map gone
    pinned = s2.cached_artifact("http://r/p.tpp.json", digest=d)
    assert pinned is not None and pinned.read_bytes() == b"bundle-bytes"


def test_quarantined_temp_files_are_not_requarantined(tmp_path):
    """Regression: the stray-temp sweep must not re-quarantine files
    already inside quarantine/ — that would count phantom corruption on
    every boot and grow the filename forever."""
    StateStore(tmp_path)  # layout
    (tmp_path / "manifests.journal.tmp.1.0").write_bytes(b"torn")
    assert StateStore(tmp_path).stats()["fsck_quarantined"] == 1
    assert StateStore(tmp_path).stats()["fsck_quarantined"] == 0
    assert StateStore(tmp_path).stats()["fsck_quarantined"] == 0
    assert len(list((tmp_path / StateStore.QUARANTINE_DIR).iterdir())) == 1


def test_manifest_persists_the_yaml_the_reload_actually_read(tmp_path):
    """TOCTOU regression: a policies.yml rewrite landing while the
    candidate compiles/canaries must NOT leak into the promoted epoch's
    manifest — the manifest persists the bytes the reload parsed, so a
    warm boot can never pin artifacts against a config this epoch never
    compiled or canaried."""
    from policy_server_tpu.server import PolicyServer

    policies_path = tmp_path / "policies.yml"
    v1 = "priv:\n  module: builtin://pod-privileged\n"
    policies_path.write_text(v1)
    cfg = _drill_config(tmp_path, policies_path)
    srv = PolicyServer.new_from_config(cfg)
    try:
        lifecycle = srv.lifecycle
        store = srv.state.statestore
        orig_read = lifecycle._read_policies

        def racy_read():
            result = orig_read()
            # the rewrite lands AFTER the reload's read, DURING the
            # compile/canary window the real race spans
            policies_path.write_text(
                "rogue:\n  module: builtin://always-unhappy\n"
            )
            return result

        lifecycle._read_policies = racy_read
        lifecycle.reload(reason="toctou-test")
        m = store.last_good_manifest()
        assert m["epoch"] == 1 and m["policies_yaml"] == v1
        assert "rogue" not in m["policy_ids"]
    finally:
        srv.lifecycle.shutdown()
