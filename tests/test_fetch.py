"""Fetch layer tests: IR serde roundtrip, artifact loading + settings
binding, Ed25519 signature verification (verification.yml semantics),
file:// and https:// and registry:// (fake OCI) downloads, and end-to-end
server bootstrap from a fetched artifact — mirroring the reference's
integration tests that pull real policies (tests/common/mod.rs:29-105) with
a local registry standing in for ghcr.io."""

from __future__ import annotations

import base64
import http.server
import json
import threading

import pytest

# fetch/verify imports cryptography at module load: in dependency-light
# containers the whole module must SKIP, not error (graftcheck round 8)
pytest.importorskip("cryptography")

from policy_server_tpu.config.sources import Sources
from policy_server_tpu.config.verification import VerificationConfig
from policy_server_tpu.fetch import (
    ArtifactError,
    Downloader,
    dump_artifact,
    load_artifact,
    sign_artifact_bytes,
    verify_artifact,
)
from policy_server_tpu.fetch.verify import VerificationError
from policy_server_tpu.models.policy import parse_policy_entry
from policy_server_tpu.ops import ir, serde
from policy_server_tpu.ops.ir import DType, Elem, Path as IRPath

from cryptography.hazmat.primitives.asymmetric.ed25519 import Ed25519PrivateKey
from cryptography.hazmat.primitives import serialization


# -- serde ------------------------------------------------------------------


def sample_exprs():
    return [
        ir.eq(IRPath("request.operation"), "CREATE"),
        ir.in_set(IRPath("request.namespace"), ["a", "b"]),
        ir.AnyOf(
            IRPath("request.object.spec.containers"),
            ir.eq(Elem("securityContext.privileged", DType.BOOL), True)
            & ~ir.Exists(Elem("image")),
        ),
        ir.CountOf(
            IRPath("request.object.spec.containers"),
            ir.matches_glob(Elem("image"), "*:latest"),
        )
        .__gt__ if False else ir.gt(
            ir.CountOf(
                IRPath("request.object.spec.containers"),
                ir.matches_glob(Elem("image"), "*:latest"),
            ),
            0,
        ),
        ir.AllOf(
            IRPath("request.object.metadata.labels"),
            ir.Not(ir.in_set(Elem("__key__"), ["bad"])),
        ),
    ]


def test_serde_roundtrip():
    for expr in sample_exprs():
        doc = serde.expr_to_json(expr)
        back = serde.expr_from_json(json.loads(json.dumps(doc)))
        assert back == expr


def test_serde_setting_refs():
    doc = {
        "op": "in_set",
        "operand": {"op": "path", "path": "request.namespace", "dtype": "id"},
        "values": {"$setting": "denied"},
        "dtype": "id",
    }
    e = serde.expr_from_json(doc, {"denied": ["x", "y"]})
    assert e == ir.in_set(IRPath("request.namespace"), ["x", "y"])
    with pytest.raises(serde.SettingsBindingError):
        serde.expr_from_json(doc, {})
    doc["values"] = {"$setting": "denied", "default": ["z"]}
    e = serde.expr_from_json(doc, {})
    assert e == ir.in_set(IRPath("request.namespace"), ["z"])


# -- artifacts --------------------------------------------------------------


def bundle_bytes(required=()) -> bytes:
    from policy_server_tpu.ops.compiler import Rule

    # paths are relative to the AdmissionRequest document (the validate
    # payload root), like the builtins' (e.g. policies/library.py NAMESPACE)
    doc = dump_artifact(
        "deny-namespaces",
        [
            Rule(
                "denied-ns",
                ir.in_set(IRPath("namespace"), ["blocked"]),
                "namespace is blocked",
            )
        ],
        required_settings=tuple(required),
    )
    if required:
        doc["rules"][0]["condition"]["values"] = {"$setting": required[0]}
    return json.dumps(doc).encode()


def test_artifact_load_and_build(tmp_path):
    p = tmp_path / "pol.tpp.json"
    p.write_bytes(bundle_bytes())
    module = load_artifact(p)
    assert module.name == "deny-namespaces"
    program = module.build({})
    assert len(program.rules) == 1
    assert module.validate_settings({}).valid


def test_artifact_required_settings(tmp_path):
    p = tmp_path / "pol.tpp.json"
    p.write_bytes(bundle_bytes(required=("denied",)))
    module = load_artifact(p)
    resp = module.validate_settings({})
    assert not resp.valid and "denied" in resp.message
    assert module.validate_settings({"denied": ["a"]}).valid


def test_artifact_accepts_wasm_with_known_abi(tmp_path):
    """Wasm payloads load as host-executed policy modules (multi-ABI,
    evaluation/wasm_policy.py); an empty module with no policy ABI is
    still a clear initialization error."""
    from policy_server_tpu.policies.wasm_oracle import oracle_wasm

    p = tmp_path / "pol.wasm"
    p.write_bytes(oracle_wasm("always-happy"))
    module = load_artifact(p)
    assert module.abi == "wapc"
    assert module.name == "pol"

    bare = tmp_path / "bare.wasm"
    bare.write_bytes(b"\x00asm\x01\x00\x00\x00")
    with pytest.raises(ArtifactError, match="ABI"):
        load_artifact(bare)


def test_artifact_minimum_version(tmp_path):
    doc = json.loads(bundle_bytes())
    doc["metadata"]["minimumFrameworkVersion"] = "999.0"
    p = tmp_path / "pol.tpp.json"
    p.write_text(json.dumps(doc))
    with pytest.raises(ArtifactError, match="999.0"):
        load_artifact(p)


# -- signatures -------------------------------------------------------------


def keypair():
    key = Ed25519PrivateKey.generate()
    priv = key.private_bytes(
        serialization.Encoding.PEM,
        serialization.PrivateFormat.PKCS8,
        serialization.NoEncryption(),
    )
    pub = key.public_key().public_bytes(
        serialization.Encoding.PEM, serialization.PublicFormat.SubjectPublicKeyInfo
    )
    return priv, pub


def write_signed(tmp_path, data: bytes, priv: bytes, annotations=None):
    from policy_server_tpu.fetch.verify import make_signature_entry

    artifact = tmp_path / "pol.tpp.json"
    artifact.write_bytes(data)
    entry = make_signature_entry(priv, data, keyid="k1", annotations=annotations)
    (tmp_path / "pol.tpp.json.sig.json").write_text(
        json.dumps({"signatures": [entry]})
    )
    return artifact


def verification_config(pub: bytes, annotations=None) -> VerificationConfig:
    doc = {
        "apiVersion": "v1",
        "allOf": [
            {
                "kind": "pubKey",
                "owner": "tester",
                "key": pub.decode(),
                **({"annotations": annotations} if annotations else {}),
            }
        ],
    }
    return VerificationConfig.from_dict(doc)


def test_signature_verification_pass_and_fail(tmp_path):
    priv, pub = keypair()
    artifact = write_signed(tmp_path, bundle_bytes(), priv)
    digest = verify_artifact(artifact, verification_config(pub))
    assert len(digest) == 64

    # tampered artifact fails
    artifact.write_bytes(bundle_bytes() + b" ")
    with pytest.raises(VerificationError):
        verify_artifact(artifact, verification_config(pub))

    # wrong key fails
    _, other_pub = keypair()
    artifact.write_bytes(bundle_bytes())
    with pytest.raises(VerificationError):
        verify_artifact(artifact, verification_config(other_pub))


def test_signature_annotations_must_match(tmp_path):
    priv, pub = keypair()
    artifact = write_signed(tmp_path, bundle_bytes(), priv, {"env": "prod"})
    verify_artifact(artifact, verification_config(pub, {"env": "prod"}))
    with pytest.raises(VerificationError):
        verify_artifact(artifact, verification_config(pub, {"env": "staging"}))


def test_sidecar_annotations_are_signed(tmp_path):
    """Annotations live inside the SIGNED payload: editing the sidecar to
    graft a different annotation set onto an authentic signature must not
    satisfy an annotation requirement (round-1 advisor finding)."""
    priv, pub = keypair()
    artifact = write_signed(tmp_path, bundle_bytes(), priv, {"env": "staging"})
    sidecar = tmp_path / "pol.tpp.json.sig.json"
    doc = json.loads(sidecar.read_text())
    # attacker edits the unsigned envelope, claiming env=prod
    doc["signatures"][0]["annotations"] = {"env": "prod"}
    sidecar.write_text(json.dumps(doc))
    with pytest.raises(VerificationError):
        verify_artifact(artifact, verification_config(pub, {"env": "prod"}))

    # ...and tampering with the payload itself breaks the signature
    payload = json.loads(base64.b64decode(doc["signatures"][0]["payload"]))
    payload["optional"] = {"env": "prod"}
    doc["signatures"][0]["payload"] = base64.b64encode(
        json.dumps(payload, sort_keys=True, separators=(",", ":")).encode()
    ).decode()
    sidecar.write_text(json.dumps(doc))
    with pytest.raises(VerificationError):
        verify_artifact(artifact, verification_config(pub, {"env": "prod"}))


def test_signature_bound_to_digest_not_reusable(tmp_path):
    """A valid signature for artifact A attached to artifact B must fail:
    the signed payload pins A's digest."""
    priv, pub = keypair()
    write_signed(tmp_path, bundle_bytes(), priv)
    other = tmp_path / "other.tpp.json"
    other.write_bytes(bundle_bytes() + b"  ")
    (tmp_path / "other.tpp.json.sig.json").write_text(
        (tmp_path / "pol.tpp.json.sig.json").read_text()
    )
    with pytest.raises(VerificationError):
        verify_artifact(other, verification_config(pub))


def test_downloader_carries_sidecar_to_store(tmp_path):
    """Round-1 advisor HIGH finding: the sidecar must travel with the
    artifact into the content-addressed store, so verification of the
    STORED path sees the signatures."""
    priv, pub = keypair()
    src_dir = tmp_path / "src"
    src_dir.mkdir()
    artifact = write_signed(src_dir, bundle_bytes(), priv, {"env": "prod"})

    store = tmp_path / "store"
    dl = Downloader(verification_config=verification_config(pub))
    fetched = dl.download_policies(
        {"p": parse_policy_entry("p", {"module": f"file://{artifact}"})},
        store,
    )
    stored = fetched.ok(f"file://{artifact}")
    assert stored.parent == store
    assert (store / (stored.name + ".sig.json")).exists()
    # end-to-end: verify against the STORED path (this was returning [] and
    # failing every verification-enabled deployment)
    verify_artifact(stored, verification_config(pub, {"env": "prod"}))


def test_keyless_kinds_fail_loudly(tmp_path):
    priv, pub = keypair()
    artifact = write_signed(tmp_path, bundle_bytes(), priv)
    config = VerificationConfig.from_dict(
        {
            "apiVersion": "v1",
            "allOf": [
                {
                    "kind": "githubAction",
                    "owner": "kubewarden",
                }
            ],
        }
    )
    with pytest.raises(VerificationError, match="keyless"):
        verify_artifact(artifact, config)


# -- downloader -------------------------------------------------------------


class _Registry(http.server.BaseHTTPRequestHandler):
    """Minimal OCI registry + plain HTTP file host."""

    artifact = bundle_bytes()
    token_required = True
    # optional headers injected on manifest responses (digest-verify tests)
    manifest_headers: dict = {}

    def log_message(self, *a):  # silence
        pass

    def do_GET(self):
        import hashlib

        digest = "sha256:" + hashlib.sha256(self.artifact).hexdigest()
        if self.path == "/plain/pol.tpp.json":
            self._ok(self.artifact, "application/json")
        elif self.path.startswith("/token"):
            self._ok(json.dumps({"token": "tok123"}).encode(), "application/json")
        elif self.path.startswith("/v2/") and "/manifests/" in self.path:
            if self.token_required and "Bearer tok123" not in self.headers.get(
                "Authorization", ""
            ):
                self.send_response(401)
                self.send_header(
                    "WWW-Authenticate",
                    f'Bearer realm="http://{self.headers["Host"]}/token",'
                    f'service="registry",scope="repository:pull"',
                )
                self.end_headers()
                return
            manifest = {
                "schemaVersion": 2,
                "layers": [
                    {
                        "mediaType": "application/vnd.tpp.policy.v1+json",
                        "digest": digest,
                        "size": len(self.artifact),
                    }
                ],
            }
            self._ok(
                json.dumps(manifest).encode(),
                "application/vnd.oci.image.manifest.v1+json",
                extra_headers=self.manifest_headers,
            )
        elif self.path.startswith("/v2/") and "/blobs/" in self.path:
            self._ok(self.artifact, "application/octet-stream")
        else:
            self.send_response(404)
            self.end_headers()

    def _ok(self, body: bytes, ctype: str, extra_headers: dict | None = None):
        self.send_response(200)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        for k, v in (extra_headers or {}).items():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(body)


@pytest.fixture(scope="module")
def registry():
    server = http.server.ThreadingHTTPServer(("127.0.0.1", 0), _Registry)
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    yield f"127.0.0.1:{server.server_port}"
    server.shutdown()


def insecure_sources(host: str) -> Sources:
    return Sources(insecure_sources=frozenset({host}))


def test_fetch_file_scheme(tmp_path):
    src = tmp_path / "pol.tpp.json"
    src.write_bytes(bundle_bytes())
    d = Downloader()
    path = d.fetch_policy(f"file://{src}", tmp_path / "store")
    assert path.read_bytes() == bundle_bytes()
    # content-addressed: same bytes → same path
    again = d.fetch_policy(f"file://{src}", tmp_path / "store")
    assert again == path


def test_fetch_http_scheme(tmp_path, registry):
    d = Downloader(sources=insecure_sources(registry.split(":")[0]))
    path = d.fetch_policy(
        f"http://{registry}/plain/pol.tpp.json", tmp_path / "store"
    )
    assert path.read_bytes() == bundle_bytes()


def test_fetch_registry_scheme_with_token_flow(tmp_path, registry):
    d = Downloader(sources=insecure_sources(registry))
    path = d.fetch_policy(
        f"registry://{registry}/kubewarden/policies/deny-ns:v1.0",
        tmp_path / "store",
    )
    assert path.read_bytes() == bundle_bytes()
    assert path.suffix == ".json"


def test_download_policies_collects_errors(tmp_path):
    policies = {
        "good": parse_policy_entry("good", {"module": "builtin://always-happy"}),
        "bad": parse_policy_entry(
            "bad", {"module": "file:///does/not/exist.tpp.json"}
        ),
    }
    d = Downloader()
    result = d.download_policies(policies, tmp_path / "store")
    assert "file:///does/not/exist.tpp.json" in result.errors
    # builtins are not fetched
    assert "builtin://always-happy" not in result.fetched


# -- end to end: bootstrap from a fetched artifact --------------------------


def test_server_bootstraps_fetched_artifact(tmp_path):
    from policy_server_tpu.config.config import Config
    from policy_server_tpu.evaluation.environment import EvaluationEnvironmentBuilder
    from policy_server_tpu.fetch import make_module_resolver
    from policy_server_tpu.models import AdmissionReviewRequest, ValidateRequest

    src = tmp_path / "pol.tpp.json"
    src.write_bytes(bundle_bytes())
    policies = {
        "deny-ns": parse_policy_entry("deny-ns", {"module": f"file://{src}"})
    }
    config = Config(
        policies=policies, policies_download_dir=str(tmp_path / "store")
    )
    resolver = make_module_resolver(config)
    env = EvaluationEnvironmentBuilder(
        backend="jax", module_resolver=resolver
    ).build(policies)

    from conftest import build_admission_review_dict

    doc = build_admission_review_dict()
    doc["request"]["namespace"] = "blocked"
    req = ValidateRequest.from_admission(
        AdmissionReviewRequest.from_dict(doc).request
    )
    resp = env.validate("deny-ns", req)
    assert not resp.allowed
    assert resp.status.message == "namespace is blocked"
    doc["request"]["namespace"] = "fine"
    req2 = ValidateRequest.from_admission(
        AdmissionReviewRequest.from_dict(doc).request
    )
    assert env.validate("deny-ns", req2).allowed


def test_manifest_digest_token_auth_flow(registry):
    """Downloader.manifest_digest resolves a ref through the same
    token-challenge flow registry:// pulls use; the digest matches the
    sha256 of the manifest the fake registry serves (it sends no
    Docker-Content-Digest header, so the body hash is the answer)."""
    import hashlib as _hashlib

    d = Downloader(sources=insecure_sources(registry))
    digest = d.manifest_digest(f"{registry}/kubewarden/policies/deny-ns:v1.0")
    art = _Registry.artifact
    manifest = {
        "schemaVersion": 2,
        "layers": [
            {
                "mediaType": "application/vnd.tpp.policy.v1+json",
                "digest": "sha256:" + _hashlib.sha256(art).hexdigest(),
                "size": len(art),
            }
        ],
    }
    expected = "sha256:" + _hashlib.sha256(
        json.dumps(manifest).encode()
    ).hexdigest()
    assert digest == expected

    # an unknown repository is an actual registry failure → FetchError
    from policy_server_tpu.fetch.downloader import FetchError

    with pytest.raises(FetchError):
        d.manifest_digest(f"{registry.replace(':', 'x:')}/nope/nope:v0")


def test_manifest_digest_header_verified_not_trusted(registry):
    """ADVICE r5 #2: the Docker-Content-Digest header is VERIFIED against
    the sha256 of the served manifest bytes — a matching header is
    returned, a mismatching one (misbehaving registry) raises, and an
    unverifiable algorithm falls back to the client-computed digest.
    The value feeds policy verify decisions via oci/v1/manifest_digest,
    so header trust would let a registry forge provenance."""
    import hashlib as _hashlib

    from policy_server_tpu.fetch.downloader import FetchError

    d = Downloader(sources=insecure_sources(registry))
    ref = f"{registry}/kubewarden/policies/deny-ns:v1.0"
    computed = d.manifest_digest(ref)  # no header: body hash
    try:
        # 1) header agrees with the bytes → returned verbatim
        _Registry.manifest_headers = {"Docker-Content-Digest": computed}
        assert d.manifest_digest(ref) == computed
        # 2) header disagrees → rejected, never trusted
        _Registry.manifest_headers = {
            "Docker-Content-Digest": "sha256:" + "0" * 64
        }
        with pytest.raises(FetchError, match="digest mismatch"):
            d.manifest_digest(ref)
        # 3) unverifiable algorithm → fall back to the computed sha256
        _Registry.manifest_headers = {
            "Docker-Content-Digest": "nothash:abcdef"
        }
        assert d.manifest_digest(ref) == computed
        # 3b) variable-length digests (shake_*) are unverifiable too:
        # hashlib constructs them but hexdigest() needs a length — must
        # fall back, not leak a TypeError past the FetchError contract
        _Registry.manifest_headers = {
            "Docker-Content-Digest": "shake_128:abcdef"
        }
        assert d.manifest_digest(ref) == computed
        # 4) a non-sha256 but supported algorithm is verified on its own
        # terms
        manifest_bytes = None
        _Registry.manifest_headers = {}
        # recover the exact served bytes via the computed digest check
        art = _Registry.artifact
        manifest_bytes = json.dumps({
            "schemaVersion": 2,
            "layers": [{
                "mediaType": "application/vnd.tpp.policy.v1+json",
                "digest": "sha256:" + _hashlib.sha256(art).hexdigest(),
                "size": len(art),
            }],
        }).encode()
        sha512 = "sha512:" + _hashlib.sha512(manifest_bytes).hexdigest()
        _Registry.manifest_headers = {"Docker-Content-Digest": sha512}
        assert d.manifest_digest(ref) == sha512
    finally:
        _Registry.manifest_headers = {}
