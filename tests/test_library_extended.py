"""Tests for the round-3 builtin additions (user-group-psp, sysctl-psp,
containers-resource-limits, environment-variable-policy, selinux-psp):
verdict semantics on both backends must agree (the per-family
mini-differential), plus settings validation."""

from __future__ import annotations

import pytest

from policy_server_tpu.evaluation.environment import EvaluationEnvironmentBuilder
from policy_server_tpu.evaluation.errors import BootstrapFailure
from policy_server_tpu.models import AdmissionReviewRequest, ValidateRequest
from policy_server_tpu.models.policy import parse_policy_entry

from conftest import build_admission_review_dict


def review_with(obj: dict) -> ValidateRequest:
    doc = build_admission_review_dict()
    doc["request"]["object"] = obj
    return ValidateRequest.from_admission(
        AdmissionReviewRequest.from_dict(doc).request
    )


def build_pair(name: str, module: str, settings: dict):
    entry = {"module": module, **({"settings": settings} if settings else {})}
    envs = []
    for backend in ("jax", "oracle"):
        envs.append(
            EvaluationEnvironmentBuilder(backend=backend).build(
                {name: parse_policy_entry(name, entry)}
            )
        )
    return envs


def check(name: str, module: str, settings: dict, cases: list[tuple[dict, bool]]):
    jax_env, oracle_env = build_pair(name, module, settings)
    for obj, expect_allowed in cases:
        a = jax_env.validate(name, review_with(obj))
        b = oracle_env.validate(name, review_with(obj))
        assert a.to_dict() == b.to_dict(), obj
        assert a.allowed is expect_allowed, (obj, a.status and a.status.message)


def test_user_group_psp_ranges():
    settings = {
        "run_as_user": {"rule": "MustRunAs",
                        "ranges": [{"min": 1000, "max": 2000}]},
        "run_as_group": {"rule": "MustRunAsNonRoot"},
    }
    check("ug", "builtin://user-group-psp", settings, [
        ({"spec": {"securityContext": {"runAsUser": 1500}}}, True),
        ({"spec": {"securityContext": {"runAsUser": 999}}}, False),
        ({"spec": {"containers": [
            {"securityContext": {"runAsUser": 2001}}]}}, False),
        ({"spec": {"securityContext": {"runAsGroup": 0}}}, False),
        ({"spec": {"securityContext": {"runAsGroup": 5}}}, True),
        ({"spec": {}}, True),  # absent ids pass (defaulting chain's job)
    ])


def test_user_group_psp_settings_validation():
    for bad in (
        {"run_as_user": {"rule": "MustRunAs"}},  # no ranges
        {"run_as_user": {"rule": "MustRunAs",
                         "ranges": [{"min": None, "max": 10}]}},
        {"run_as_user": {"rule": "MustRunAs",
                         "ranges": [{"min": 10, "max": 1}]}},
    ):
        with pytest.raises(BootstrapFailure):
            EvaluationEnvironmentBuilder(backend="jax").build(
                {"ug": parse_policy_entry("ug", {
                    "module": "builtin://user-group-psp", "settings": bad,
                })}
            )


def test_user_group_psp_large_uid_precision():
    """UIDs above 2^24 must classify exactly (float32 would collapse
    16777217 onto 16777216 and admit an out-of-range id)."""
    settings = {"run_as_user": {"rule": "MustRunAs",
                                "ranges": [{"min": 1000, "max": 16777216}]}}
    check("ug-precision", "builtin://user-group-psp", settings, [
        ({"spec": {"securityContext": {"runAsUser": 16777216}}}, True),
        ({"spec": {"securityContext": {"runAsUser": 16777217}}}, False),
    ])


def test_sysctl_psp():
    settings = {
        "forbidden_sysctls": ["kernel.msg*", "net.ipv4.ip_forward"],
        "allowed_unsafe_sysctls": ["kernel.msgmax"],
    }
    sysctl = lambda name: {"spec": {"securityContext": {"sysctls": [
        {"name": name, "value": "1"}]}}}
    check("sys", "builtin://sysctl-psp", settings, [
        (sysctl("net.ipv4.ip_forward"), False),
        (sysctl("kernel.msgmnb"), False),     # matches the glob
        (sysctl("kernel.msgmax"), True),      # explicitly allowed
        (sysctl("vm.swappiness"), True),
        ({"spec": {}}, True),
    ])


def test_containers_resource_limits():
    check("lim", "builtin://containers-resource-limits", {}, [
        ({"spec": {"containers": [
            {"resources": {"limits": {"cpu": "1", "memory": "1Gi"}}}]}}, True),
        ({"spec": {"containers": [
            {"resources": {"limits": {"cpu": "1"}}}]}}, False),
        ({"spec": {"containers": [{}]}}, False),
        ({"spec": {"containers": []}}, True),
    ])
    check("lim2", "builtin://containers-resource-limits",
          {"require_memory": False}, [
        ({"spec": {"containers": [
            {"resources": {"limits": {"cpu": "1"}}}]}}, True),
    ])


def test_environment_variable_policy():
    settings = {"denied_names": ["AWS_SECRET_ACCESS_KEY", "DEBUG"]}
    check("env", "builtin://environment-variable-policy", settings, [
        ({"spec": {"containers": [
            {"env": [{"name": "PATH", "value": "/bin"}]}]}}, True),
        ({"spec": {"containers": [
            {"env": [{"name": "DEBUG", "value": "1"}]}]}}, False),
        ({"spec": {"containers": [
            {"env": [{"name": "A"}]},
            {"env": [{"name": "AWS_SECRET_ACCESS_KEY"}]}]}}, False),
        ({"spec": {"containers": [{}]}}, True),
    ])


def test_selinux_psp():
    settings = {"rule": "MustRunAs", "level": "s0:c123,c456", "type": "spc_t"}
    check("se", "builtin://selinux-psp", settings, [
        ({"spec": {"securityContext": {"seLinuxOptions": {
            "level": "s0:c123,c456", "type": "spc_t"}}}}, True),
        ({"spec": {"securityContext": {"seLinuxOptions": {
            "level": "s0:c1,c2"}}}}, False),
        ({"spec": {"containers": [{"securityContext": {"seLinuxOptions": {
            "type": "other_t"}}}]}}, False),
        ({"spec": {}}, True),  # nothing set → nothing to contradict
    ])
    check("se2", "builtin://selinux-psp", {"rule": "RunAsAny"}, [
        ({"spec": {"securityContext": {"seLinuxOptions": {
            "level": "anything"}}}}, True),
    ])
