"""Multi-tenant serving (tenancy.py + runtime/scheduler.py, round 16).

The contract under test:

* tenants manifest parsing (per-tenant policies files, quotas, weights,
  deadline classes; reserved names rejected);
* token-bucket admission: rows/s + burst + in-flight cap, 429 +
  Retry-After with tenant-labelled counters;
* weighted-fair dispatch scheduler: live before audit, grant counts
  converging to weight ratios, bounded waits;
* tenant-scoped failpoints (thread-local ambient scope);
* end-to-end routing: /validate/{tenant}/{policy_id} picks the tenant,
  every un-prefixed URL stays the default tenant, unknown tenants 404
  identically on both frontends;
* hard isolation: per-tenant verdict caches, shadow-canary rings, and
  epoch lifecycles never observe another tenant's state; one tenant's
  quota overload sheds at ITS front door while the others keep serving;
* honest readiness: /readiness/{tenant} per tenant, the global probe
  503 only when EVERY tenant is degraded (partial-outage regression).
"""

from __future__ import annotations

import threading
import time

import pytest
import requests

from policy_server_tpu import failpoints
from policy_server_tpu.runtime.batcher import ShedError
from policy_server_tpu.runtime import scheduler as fair
from policy_server_tpu.runtime.scheduler import FairDispatchScheduler
from policy_server_tpu.tenancy import (
    DEFAULT_TENANT,
    Tenant,
    TenantAdmission,
    TenantConfigError,
    TenantManager,
    TenantSpec,
    TenantState,
    read_tenants_file,
    split_tenant_path,
    unknown_tenant_message,
)


@pytest.fixture(autouse=True)
def clean_failpoints():
    failpoints.reset()
    yield
    failpoints.reset()


# ---------------------------------------------------------------------------
# Manifest parsing
# ---------------------------------------------------------------------------


def _write_manifest(tmp_path, text: str):
    p = tmp_path / "tenants.yml"
    p.write_text(text, encoding="utf-8")
    return p


def test_manifest_parses_specs_and_resolves_relative_paths(tmp_path):
    (tmp_path / "a.yml").write_text("x:\n  module: builtin://always-happy\n")
    manifest = read_tenants_file(_write_manifest(tmp_path, """\
tenants:
  team-a:
    policies: a.yml
    weight: 2.5
    quota-rows-per-second: 100
    quota-burst: 25
    max-inflight: 64
    request-timeout-ms: 5000
    degraded-mode: reject
default:
  weight: 0.5
  quota-rows-per-second: 10
max-concurrent-dispatches: 3
"""))
    spec = manifest.tenants["team-a"]
    assert spec.policies_path == str(tmp_path / "a.yml")
    assert spec.weight == 2.5
    assert spec.quota_rows_per_second == 100.0
    assert spec.quota_burst == 25.0
    assert spec.max_inflight == 64
    assert spec.request_timeout_ms == 5000.0
    assert spec.degraded_mode == "reject"
    assert manifest.default.weight == 0.5
    assert manifest.default.quota_rows_per_second == 10.0
    assert manifest.max_concurrent_dispatches == 3


@pytest.mark.parametrize("text", [
    "tenants: {}\n",                                      # empty
    "tenants:\n  default:\n    policies: a.yml\n",        # reserved
    "tenants:\n  reports:\n    policies: a.yml\n",        # shadows route
    "tenants:\n  t:\n    policies: a.yml\n    bogus: 1\n",  # unknown key
    "tenants:\n  t:\n    policies: a.yml\n    weight: 0\n",  # bad weight
    "tenants:\n  t: {}\n",                                # missing policies
    "tenants:\n  t:\n    policies: a.yml\ndefault:\n  policies: b.yml\n",
    "tenants:\n  t:\n    policies: a.yml\nmax-concurrent-dispatches: 0\n",
])
def test_manifest_rejects_malformed(tmp_path, text):
    with pytest.raises(TenantConfigError):
        read_tenants_file(_write_manifest(tmp_path, text))


def test_split_tenant_path():
    assert split_tenant_path("pod-privileged") == (None, "pod-privileged")
    assert split_tenant_path("team-a/pol") == ("team-a", "pol")
    # deeper nesting stays with the tenant segment split-once; the
    # policy-id lookup then 404s naturally
    assert split_tenant_path("a/b/c") == ("a", "b/c")


# ---------------------------------------------------------------------------
# Admission quota
# ---------------------------------------------------------------------------


def test_token_bucket_sheds_past_burst_and_refills():
    adm = TenantAdmission("t", rows_per_second=50.0, burst=5.0)
    adm.admit(5)
    with pytest.raises(ShedError) as e:
        adm.admit(1)
    assert e.value.retry_after_seconds > 0
    stats = adm.stats()
    assert stats["admitted_rows"] == 5
    assert stats["quota_sheds"] == 1
    time.sleep(0.1)  # 50 rows/s -> ~5 tokens back
    adm.admit(2)
    assert adm.stats()["admitted_rows"] == 7


def test_token_bucket_admits_bursts_larger_than_depth():
    """A submit burst bigger than the bucket DEPTH (the native frontend
    admits whole poll bursts as units) still admits when the bucket is
    full — the balance goes into deficit and later admissions shed
    until the deficit repays at ``rate``, keeping the average bounded
    and the advertised Retry-After honest."""
    adm = TenantAdmission("t", rows_per_second=100.0, burst=8.0)
    adm.admit(16)  # bucket 8 - 16 -> deficit of 8
    assert adm.stats()["admitted_rows"] == 16
    with pytest.raises(ShedError) as e:
        adm.admit(1)  # in deficit: sheds, with a FINITE honest retry
    assert 0 < e.value.retry_after_seconds < 1.0
    time.sleep(0.15)  # 100 rows/s repays the -8 deficit
    adm.admit(1)
    assert adm.stats()["admitted_rows"] == 17


def test_inflight_cap_sheds_and_release_reopens():
    adm = TenantAdmission("t", max_inflight=3)
    adm.admit(3)
    with pytest.raises(ShedError):
        adm.admit(1)
    assert adm.stats()["inflight_sheds"] == 1
    adm.release(2)
    adm.admit(2)
    assert adm.stats()["inflight"] == 3
    # over-release floors at zero (shutdown double-resolve tolerance)
    adm.release(100)
    assert adm.stats()["inflight"] == 0


def test_tenant_admission_failpoint_fires_in_admit():
    adm = TenantAdmission("t", rows_per_second=1000.0)
    with failpoints.active(
        "tenant.admission",
        lambda: (_ for _ in ()).throw(failpoints.FailpointError("boom")),
    ):
        with pytest.raises(failpoints.FailpointError):
            adm.admit(1)
    assert failpoints.fired_count("tenant.admission") == 1
    # nothing was admitted: the fault precedes the quota math
    assert adm.stats()["admitted_rows"] == 0


# ---------------------------------------------------------------------------
# Tenant-scoped failpoints
# ---------------------------------------------------------------------------


def test_failpoint_scope_is_thread_local_and_restored():
    hits: list[str] = []
    failpoints.set_failpoint(
        "tenant.admission", lambda: hits.append("hit"), scope="tenant-a"
    )
    failpoints.fire("tenant.admission")  # unscoped thread: no-op
    assert hits == []
    with failpoints.scope("tenant-b"):
        failpoints.fire("tenant.admission")  # other tenant: no-op
        with failpoints.scope("tenant-a"):
            failpoints.fire("tenant.admission")  # match
        assert failpoints.current_scope() == "tenant-b"
    assert failpoints.current_scope() is None
    assert hits == ["hit"]

    # another thread never inherits the scope
    def other():
        failpoints.fire("tenant.admission")

    t = threading.Thread(target=other)
    t.start()
    t.join()
    assert hits == ["hit"]


# ---------------------------------------------------------------------------
# Weighted-fair dispatch scheduler
# ---------------------------------------------------------------------------


def test_scheduler_fast_path_and_release():
    s = FairDispatchScheduler(max_concurrent=2)
    assert s.acquire("a")
    assert s.acquire("b")
    granted = []
    t = threading.Thread(
        target=lambda: granted.append(s.acquire("c", timeout=5))
    )
    t.start()
    time.sleep(0.05)
    assert granted == []  # cap reached: c waits
    s.release("a")
    t.join(timeout=5)
    assert granted == [True]
    stats = s.stats()
    assert stats["a"]["grants"] == 1
    assert stats["c"]["grants"] == 1
    assert stats["c"]["wait_ns"] > 0


def test_scheduler_timeout_and_abort():
    s = FairDispatchScheduler(max_concurrent=1)
    assert s.acquire("a")
    t0 = time.perf_counter()
    assert not s.acquire("b", timeout=0.15)
    assert time.perf_counter() - t0 < 2.0
    assert not s.acquire("b", should_abort=lambda: True)
    # releasing after abandoned waiters must not wedge
    s.release("a")
    assert s.acquire("b")


def test_scheduler_weighted_shares_converge():
    """With the slot permanently contended, grant counts track the
    weight ratio (stride scheduling)."""
    s = FairDispatchScheduler(
        max_concurrent=1, weights={"heavy": 3.0, "light": 1.0}
    )
    done = threading.Event()
    counts = {"heavy": 0, "light": 0}
    lock = threading.Lock()

    def worker(name: str) -> None:
        while not done.is_set():
            if s.acquire(name, timeout=1.0, should_abort=done.is_set):
                with lock:
                    counts[name] += 1
                s.release(name)

    threads = [
        threading.Thread(target=worker, args=(n,), daemon=True)
        for n in ("heavy", "light") for _ in range(2)
    ]
    for t in threads:
        t.start()
    time.sleep(0.8)
    done.set()
    for t in threads:
        t.join(timeout=5)
    total = counts["heavy"] + counts["light"]
    assert total > 50
    share = counts["heavy"] / total
    # 3:1 weights -> 0.75 share; generous band for scheduling noise
    assert 0.6 < share < 0.9, counts


def test_audit_grants_do_not_charge_the_live_share():
    """A quiet-window audit sweep must not inflate its tenant's LIVE
    virtual clock: after many AUDIT grants for tenant a, a contended
    LIVE round still grants a before a later-queued equal-weight b
    (tie broken FIFO — an audit-charged clock would hand b the slot)."""
    s = FairDispatchScheduler(
        max_concurrent=1, weights={"a": 1.0, "b": 1.0, "c": 1.0}
    )
    for _ in range(5):
        assert s.acquire("a", fair.AUDIT)
        s.release("a")
    assert s.acquire("c", fair.LIVE)  # occupy the slot
    order: list[str] = []

    def live_waiter(name: str) -> None:
        assert s.acquire(name, fair.LIVE, timeout=10)
        order.append(name)
        s.release(name)

    ta = threading.Thread(target=live_waiter, args=("a",))
    ta.start()
    time.sleep(0.05)
    tb = threading.Thread(target=live_waiter, args=("b",))
    tb.start()
    time.sleep(0.05)
    s.release("c")
    ta.join(timeout=5)
    tb.join(timeout=5)
    assert order == ["a", "b"]


def test_scheduler_audit_yields_to_live():
    s = FairDispatchScheduler(max_concurrent=1)
    assert s.acquire("a", fair.LIVE)
    order: list[str] = []

    def waiter(name: str, prio: int) -> None:
        assert s.acquire(name, prio, timeout=10)
        order.append(name)
        time.sleep(0.02)
        s.release(name)

    t_audit = threading.Thread(target=waiter, args=("aud", fair.AUDIT))
    t_audit.start()
    time.sleep(0.05)  # audit waiter queued first
    t_live = threading.Thread(target=waiter, args=("live", fair.LIVE))
    t_live.start()
    time.sleep(0.05)
    s.release("a")
    t_audit.join(timeout=5)
    t_live.join(timeout=5)
    assert order == ["live", "aud"]  # live granted first despite FIFO


# ---------------------------------------------------------------------------
# Readiness aggregation (the partial-outage regression)
# ---------------------------------------------------------------------------


def _stub_state(ready: bool) -> TenantState:
    return TenantState(name="x", ready=ready)


def test_global_readiness_503_only_when_every_tenant_degraded():
    from policy_server_tpu.api.state import ApiServerState

    state = ApiServerState(
        evaluation_environment=None, batcher=None, ready=True
    )
    mgr = TenantManager()
    mgr.add(Tenant(DEFAULT_TENANT, TenantSpec(name=DEFAULT_TENANT),
                   state, None))
    t_a = Tenant("a", TenantSpec(name="a"), _stub_state(ready=False), None)
    t_b = Tenant("b", TenantSpec(name="b"), _stub_state(ready=True), None)
    mgr.add(t_a)
    mgr.add(t_b)
    state.tenants = mgr

    # partial outage: tenant a degraded -> global stays in rotation
    status, text = state.readiness()
    assert status == 200
    assert "a" in text
    assert t_a.readiness()[0] == 503
    assert t_b.readiness()[0] == 200

    # every tenant degraded -> global 503
    t_b.state.ready = False
    state.ready = False
    status, text = state.readiness()
    assert status == 503
    assert "every tenant" in text

    # single-tenant (no manager): unchanged verdict logic
    state.tenants = None
    assert state.readiness()[0] == 503
    state.ready = True
    assert state.readiness() == (200, "ok")


# ---------------------------------------------------------------------------
# End-to-end: a real server with a 2-tenant manifest
# ---------------------------------------------------------------------------

_TENANT_POLICIES = {
    "ten-a": """\
only-a:
  module: builtin://pod-privileged
common:
  module: builtin://pod-privileged
""",
    "ten-b": """\
only-b:
  module: builtin://pod-privileged
common:
  module: builtin://pod-privileged
""",
}

_MANIFEST = """\
tenants:
  ten-a:
    policies: ten-a.yml
    weight: 1.0
  ten-b:
    policies: ten-b.yml
    weight: 2.0
  ten-q:
    policies: ten-a.yml
    quota-rows-per-second: 2
    quota-burst: 3
    max-inflight: 64
"""


def _tenant_config(tmp_dir, **overrides):
    from policy_server_tpu.config.config import read_policies_file
    from test_server import make_config

    for name, text in _TENANT_POLICIES.items():
        (tmp_dir / f"{name}.yml").write_text(text, encoding="utf-8")
    manifest_path = tmp_dir / "tenants.yml"
    manifest_path.write_text(_MANIFEST, encoding="utf-8")
    default_path = tmp_dir / "policies.yml"
    default_path.write_text(
        "pod-privileged:\n  module: builtin://pod-privileged\n",
        encoding="utf-8",
    )
    manifest = read_tenants_file(manifest_path)
    return make_config(
        policies=read_policies_file(default_path),
        policies_path=str(default_path),
        policy_timeout_seconds=5.0,
        tenants_path=str(manifest_path),
        tenants=manifest,
        # everything through the device path: the cache-isolation assert
        # below reads the encode-side dedup tiers
        host_fastpath_threshold=0,
        **overrides,
    )


@pytest.fixture(scope="module")
def tenant_server(tmp_path_factory):
    from policy_server_tpu.telemetry import metrics as metrics_mod
    from test_server import ServerHandle

    metrics_mod.reset_metrics_for_tests()
    tmp_dir = tmp_path_factory.mktemp("tenants")
    handle = ServerHandle(_tenant_config(tmp_dir))
    yield handle
    handle.stop()


def _pod_body(privileged: bool) -> dict:
    from test_server import pod_review_body

    return pod_review_body(privileged)


def test_tenant_routes_resolve_their_own_policy_sets(tenant_server):
    # default URL unchanged
    r = requests.post(
        tenant_server.url("/validate/pod-privileged"),
        json=_pod_body(False), timeout=30,
    )
    assert r.status_code == 200 and r.json()["response"]["allowed"]
    # tenant routes serve THEIR policies
    r = requests.post(
        tenant_server.url("/validate/ten-a/only-a"),
        json=_pod_body(True), timeout=30,
    )
    assert r.status_code == 200
    assert r.json()["response"]["allowed"] is False
    # a policy of tenant B does not exist for tenant A
    r = requests.post(
        tenant_server.url("/validate/ten-a/only-b"),
        json=_pod_body(False), timeout=30,
    )
    assert r.status_code == 404
    # the default set does not know tenant policies
    r = requests.post(
        tenant_server.url("/validate/only-a"),
        json=_pod_body(False), timeout=30,
    )
    assert r.status_code == 404


def test_unknown_tenant_404_with_shared_message(tenant_server):
    r = requests.post(
        tenant_server.url("/validate/nope/pod-privileged"),
        json=_pod_body(False), timeout=30,
    )
    assert r.status_code == 404
    assert r.json()["message"] == unknown_tenant_message("nope")


def test_per_tenant_and_global_readiness(tenant_server):
    for path, expect in (
        ("/readiness", 200),
        ("/readiness/ten-a", 200),
        ("/readiness/ten-b", 200),
    ):
        r = requests.get(tenant_server.readiness_url(path), timeout=10)
        assert r.status_code == expect, path
    r = requests.get(
        tenant_server.readiness_url("/readiness/nope"), timeout=10
    )
    assert r.status_code == 404


def test_quota_overload_sheds_tenant_q_while_b_serves(tenant_server):
    """Tenant Q past its 2 rows/s / burst-3 quota answers 429 +
    Retry-After; tenant B's simultaneous traffic is all 2xx — the
    noisy-neighbor front door."""
    statuses_a: list[int] = []
    retry_after_seen = []

    def flood_a():
        s = requests.Session()
        for _ in range(25):
            r = s.post(
                tenant_server.url("/validate/ten-q/common"),
                json=_pod_body(False), timeout=30,
            )
            statuses_a.append(r.status_code)
            if r.status_code == 429:
                retry_after_seen.append(r.headers.get("Retry-After"))

    statuses_b: list[int] = []

    def steady_b():
        s = requests.Session()
        for _ in range(15):
            r = s.post(
                tenant_server.url("/validate/ten-b/common"),
                json=_pod_body(False), timeout=30,
            )
            statuses_b.append(r.status_code)
            time.sleep(0.01)

    ta = threading.Thread(target=flood_a)
    tb = threading.Thread(target=steady_b)
    ta.start(); tb.start()
    ta.join(timeout=60); tb.join(timeout=60)

    assert statuses_a.count(429) >= 5, statuses_a
    assert all(s == 200 for s in statuses_b), statuses_b
    assert retry_after_seen and all(
        ra is not None and int(ra) >= 1 for ra in retry_after_seen
    )
    # tenant-labelled shed counters reached the admission object
    tenant_a = tenant_server.server.state.tenants.get("ten-q")
    assert tenant_a.admission.stats()["quota_sheds"] >= 5
    # in-flight claims fully released once the burst resolved
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        if tenant_a.admission.stats()["inflight"] == 0:
            break
        time.sleep(0.05)
    assert tenant_a.admission.stats()["inflight"] == 0


def test_cross_tenant_verdict_cache_isolation(tenant_server):
    """The same (policy name, payload) served through tenant A must
    never warm tenant B's verdict cache — the caches live in per-tenant
    environments."""
    mgr = tenant_server.server.state.tenants
    env_b = mgr.get("ten-b").state.evaluation_environment
    before_b = dict(env_b.dedup_stats)
    for _ in range(3):
        r = requests.post(
            tenant_server.url("/validate/ten-a/common"),
            json=_pod_body(True), timeout=30,
        )
        assert r.status_code == 200
    after_b = dict(env_b.dedup_stats)
    for key in (
        "blob_cache_hits", "blob_cache_misses", "cache_hits",
        "cache_misses",
    ):
        assert after_b.get(key, 0) == before_b.get(key, 0), key
    # B's first identical request is a MISS in B's own cache (nothing
    # leaked over from A's replays)
    r = requests.post(
        tenant_server.url("/validate/ten-b/common"),
        json=_pod_body(True), timeout=30,
    )
    assert r.status_code == 200
    miss_b = dict(env_b.dedup_stats)
    assert (
        miss_b.get("blob_cache_misses", 0) + miss_b.get("cache_misses", 0)
        > before_b.get("blob_cache_misses", 0)
        + before_b.get("cache_misses", 0)
    )


def test_shadow_canary_rings_are_tenant_scoped(tenant_server):
    """Each tenant's reload canary replays ITS recorded traffic only —
    the rings live on per-tenant lifecycles. Probe with unique policy
    ids (the ring records every SUBMITTED id, even unknown ones that
    later 404, which is exactly why a shared ring would leak)."""
    # unknown ids still record at batch formation, then 404 in
    # evaluation — perfect unique markers
    requests.post(
        tenant_server.url("/validate/ten-a/ring-probe-a"),
        json=_pod_body(False), timeout=30,
    )
    requests.post(
        tenant_server.url("/validate/ten-b/ring-probe-b"),
        json=_pod_body(False), timeout=30,
    )
    mgr = tenant_server.server.state.tenants

    def rings():
        ring_a = [
            pid for pid, _ in
            mgr.get("ten-a").state.lifecycle.recorder.snapshot()
        ]
        ring_b = [
            pid for pid, _ in
            mgr.get("ten-b").state.lifecycle.recorder.snapshot()
        ]
        return ring_a, ring_b

    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        ring_a, ring_b = rings()
        if "ring-probe-a" in ring_a and "ring-probe-b" in ring_b:
            break
        time.sleep(0.05)
    ring_a, ring_b = rings()
    assert "ring-probe-a" in ring_a and "ring-probe-a" not in ring_b
    assert "ring-probe-b" in ring_b and "ring-probe-b" not in ring_a
    # and the default tenant's ring saw neither probe
    default_ring = [
        pid for pid, _ in
        tenant_server.server.lifecycle.recorder.snapshot()
    ]
    assert "ring-probe-a" not in default_ring
    assert "ring-probe-b" not in default_ring


def test_per_tenant_reload_advances_one_epoch_only(tenant_server):
    mgr = tenant_server.server.state.tenants
    lc_a = mgr.get("ten-a").state.lifecycle
    lc_b = mgr.get("ten-b").state.lifecycle
    epoch_b = lc_b.current_epoch
    epoch_default = tenant_server.server.lifecycle.current_epoch
    before_a = lc_a.current_epoch
    assert lc_a.reload(reason="test") == "promoted"
    assert lc_a.current_epoch == before_a + 1
    assert lc_b.current_epoch == epoch_b
    assert tenant_server.server.lifecycle.current_epoch == epoch_default
    # the promoted epoch still serves tenant A's set
    r = requests.post(
        tenant_server.url("/validate/ten-a/only-a"),
        json=_pod_body(False), timeout=30,
    )
    assert r.status_code == 200


def test_tenant_metrics_families_exported(tenant_server):
    text = requests.get(
        tenant_server.readiness_url("/metrics"), timeout=10
    ).text
    assert 'policy_server_tenant_admitted_rows_total{tenant="ten-q"}' in text
    assert 'policy_server_tenant_shed_rows_total{tenant="ten-q"}' in text
    assert 'policy_server_tenant_policy_epoch{tenant="ten-b"}' in text
    assert 'policy_server_tenant_queue_depth{tenant="ten-a"}' in text
    assert 'policy_server_tenant_ready{tenant="default"}' in text
    assert "policy_server_tenants_serving 4.0" in text


def test_scheduler_accounts_tenant_grants(tenant_server):
    stats = tenant_server.server.state.tenants.scheduler.stats()
    # traffic flowed through both tenant batchers under the shared
    # scheduler by the time this test runs (module ordering)
    assert stats.get("ten-a", {}).get("grants", 0) > 0
    assert stats.get("ten-b", {}).get("grants", 0) > 0


# ---------------------------------------------------------------------------
# Native frontend parity (two-segment routing through C++)
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_native_frontend_tenant_routing_parity(tmp_path):
    from test_server import ServerHandle

    config = _tenant_config(tmp_path, frontend="native")
    handle = ServerHandle(config)
    try:
        if handle.server._native_frontend is None:
            pytest.skip("native frontend unavailable in this container")
        r = requests.post(
            handle.url("/validate/ten-a/only-a"),
            json=_pod_body(True), timeout=30,
        )
        assert r.status_code == 200
        assert r.json()["response"]["allowed"] is False
        # unknown tenant: the sink answers the SAME body the aiohttp
        # router produces
        r = requests.post(
            handle.url("/validate/nope/only-a"),
            json=_pod_body(False), timeout=30,
        )
        assert r.status_code == 404
        assert r.json()["message"] == unknown_tenant_message("nope")
        # three segments stay a plain 404 (no route)
        r = requests.post(
            handle.url("/validate/a/b/c"),
            json=_pod_body(False), timeout=30,
        )
        assert r.status_code == 404
        # default URL through the native frontend still serves
        r = requests.post(
            handle.url("/validate/pod-privileged"),
            json=_pod_body(False), timeout=30,
        )
        assert r.status_code == 200
    finally:
        handle.stop()


# ---------------------------------------------------------------------------
# Prefork parity (tenant ids cross the bridge as "tenant/policy")
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_prefork_workers_route_tenants_over_the_bridge(tmp_path):
    from test_server import ServerHandle

    config = _tenant_config(tmp_path, http_workers=2)
    handle = ServerHandle(config)
    try:
        # hit repeatedly: SO_REUSEPORT spreads connections over the main
        # process AND the worker, so both the in-process router and the
        # bridge path must agree on tenant routing
        for _ in range(12):
            r = requests.post(
                handle.url("/validate/ten-a/only-a"),
                json=_pod_body(True), timeout=30,
            )
            assert r.status_code == 200
            assert r.json()["response"]["allowed"] is False
            r = requests.post(
                handle.url("/validate/nope/only-a"),
                json=_pod_body(False), timeout=30,
            )
            assert r.status_code == 404
            assert r.json()["message"] == unknown_tenant_message("nope")
            r = requests.post(
                handle.url("/validate/pod-privileged"),
                json=_pod_body(False), timeout=30,
            )
            assert r.status_code == 200
    finally:
        handle.stop()
