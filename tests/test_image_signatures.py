"""verify-image-signatures tests: REAL Ed25519 verification of container
images through the host hook + context-provider pipeline (SURVEY.md §2.2
callback_handler/sigstore rows; round-2 VERDICT weak #4 — a
matching-glob-but-unsigned image must be REJECTED, not glob-accepted)."""

from __future__ import annotations

import pytest

pytest.importorskip("cryptography")

from cryptography.hazmat.primitives.asymmetric.ed25519 import Ed25519PrivateKey
from cryptography.hazmat.primitives.serialization import (
    Encoding,
    NoEncryption,
    PrivateFormat,
    PublicFormat,
)

from policy_server_tpu.evaluation.environment import EvaluationEnvironmentBuilder
from policy_server_tpu.models import AdmissionReviewRequest, ValidateRequest
from policy_server_tpu.evaluation.errors import BootstrapFailure
from policy_server_tpu.models.policy import parse_policy_entry
from policy_server_tpu.policies.images import sign_image, write_signature_bundle

from conftest import build_admission_review_dict


@pytest.fixture(scope="module")
def keypair():
    key = Ed25519PrivateKey.generate()
    priv = key.private_bytes(
        Encoding.PEM, PrivateFormat.PKCS8, NoEncryption()
    )
    pub = key.public_key().public_bytes(
        Encoding.PEM, PublicFormat.SubjectPublicKeyInfo
    )
    return priv, pub.decode()


@pytest.fixture(scope="module")
def other_keypair():
    key = Ed25519PrivateKey.generate()
    priv = key.private_bytes(Encoding.PEM, PrivateFormat.PKCS8, NoEncryption())
    return priv


def pod_with_images(*images: str) -> ValidateRequest:
    doc = build_admission_review_dict()
    doc["request"]["object"] = {
        "apiVersion": "v1",
        "kind": "Pod",
        "metadata": {"name": "p", "namespace": "default"},
        "spec": {
            "containers": [
                {"name": f"c{i}", "image": img} for i, img in enumerate(images)
            ]
        },
    }
    return ValidateRequest.from_admission(
        AdmissionReviewRequest.from_dict(doc).request
    )


def build_env(store_dir: str, pub_pem: str, backend: str = "jax"):
    entry = parse_policy_entry(
        "sig",
        {
            "module": "builtin://verify-image-signatures",
            "settings": {
                "signatures": [
                    {"image": "registry.example/trusted/*", "pubKeys": [pub_pem]}
                ],
                "signatureStore": store_dir,
            },
        },
    )
    return EvaluationEnvironmentBuilder(backend=backend).build({"sig": entry})


SIGNED = "registry.example/trusted/app:1.0"
UNSIGNED = "registry.example/trusted/evil:1.0"  # matches the glob, no signature
OUTSIDE = "docker.io/library/nginx:latest"  # matches no glob


@pytest.fixture(scope="module")
def store(tmp_path_factory, keypair):
    priv, _pub = keypair
    d = tmp_path_factory.mktemp("sigstore")
    write_signature_bundle(str(d), SIGNED, sign_image(priv, SIGNED))
    return str(d)


@pytest.mark.parametrize("backend", ["jax", "oracle"])
def test_signed_image_accepted(store, keypair, backend):
    env = build_env(store, keypair[1], backend)
    assert env.validate("sig", pod_with_images(SIGNED)).allowed


@pytest.mark.parametrize("backend", ["jax", "oracle"])
def test_glob_matching_but_unsigned_image_rejected(store, keypair, backend):
    """THE round-2 gap: matching the glob must not be enough — without a
    valid signature the image is rejected."""
    env = build_env(store, keypair[1], backend)
    resp = env.validate("sig", pod_with_images(UNSIGNED))
    assert not resp.allowed
    assert "signature verification failed" in resp.status.message
    assert UNSIGNED in resp.status.message


def test_image_outside_all_globs_rejected(store, keypair):
    env = build_env(store, keypair[1])
    resp = env.validate("sig", pod_with_images(OUTSIDE))
    assert not resp.allowed
    assert "matches no signature entry" in resp.status.message


def test_signature_by_wrong_key_rejected(tmp_path, keypair, other_keypair):
    """A bundle signed by a DIFFERENT key than the configured pubKey is
    not authentic — crypto, not presence, decides."""
    image = "registry.example/trusted/forged:1"
    write_signature_bundle(
        str(tmp_path), image, sign_image(other_keypair, image)
    )
    env = build_env(str(tmp_path), keypair[1])
    resp = env.validate("sig", pod_with_images(image))
    assert not resp.allowed
    assert "signature verification failed" in resp.status.message


def test_replayed_bundle_for_other_image_rejected(tmp_path, keypair):
    """A valid bundle for image A stored under image B's slot must fail:
    the signed payload binds the docker-reference."""
    a = "registry.example/trusted/a:1"
    b = "registry.example/trusted/b:1"
    write_signature_bundle(str(tmp_path), b, sign_image(keypair[0], a))
    env = build_env(str(tmp_path), keypair[1])
    resp = env.validate("sig", pod_with_images(b))
    assert not resp.allowed


def test_annotation_requirements_bound_to_signature(tmp_path, keypair):
    """Entry annotations must match the SIGNED annotations."""
    image = "registry.example/trusted/ann:1"
    write_signature_bundle(
        str(tmp_path), image,
        sign_image(keypair[0], image, annotations={"env": "prod"}),
    )
    entry = parse_policy_entry(
        "sig",
        {
            "module": "builtin://verify-image-signatures",
            "settings": {
                "signatures": [
                    {
                        "image": "registry.example/trusted/*",
                        "pubKeys": [keypair[1]],
                        "annotations": {"env": "staging"},  # mismatch
                    }
                ],
                "signatureStore": str(tmp_path),
            },
        },
    )
    env = EvaluationEnvironmentBuilder(backend="jax").build({"sig": entry})
    assert not env.validate("sig", pod_with_images(image)).allowed


def test_mixed_batch_signed_and_unsigned(store, keypair):
    """Batched evaluation: per-row verdicts stay independent."""
    env = build_env(store, keypair[1])
    results = env.validate_batch(
        [
            ("sig", pod_with_images(SIGNED)),
            ("sig", pod_with_images(UNSIGNED)),
            ("sig", pod_with_images(SIGNED)),
        ]
    )
    assert [r.allowed for r in results] == [True, False, True]


def test_signature_published_after_first_sight_honored(tmp_path, keypair, monkeypatch):
    """Negative results expire (NEGATIVE_TTL_SECONDS): publishing a bundle
    after an image was first rejected takes effect without a restart."""
    from policy_server_tpu.policies.images import ImageSignatureVerifier

    monkeypatch.setattr(ImageSignatureVerifier, "NEGATIVE_TTL_SECONDS", 0.0)
    image = "registry.example/trusted/late:1"
    env = build_env(str(tmp_path), keypair[1])
    assert not env.validate("sig", pod_with_images(image)).allowed
    write_signature_bundle(str(tmp_path), image, sign_image(keypair[0], image))
    assert env.validate("sig", pod_with_images(image)).allowed


def test_non_mapping_object_rejected_not_crashing(store, keypair):
    """A crafted request whose object is not a pod-shaped mapping must not
    raise — it has no containers, so no glob matches and no crypto runs;
    the policy's structural rules decide."""
    doc = build_admission_review_dict()
    doc["request"]["object"] = "not-a-pod"
    req = ValidateRequest.from_admission(
        AdmissionReviewRequest.from_dict(doc).request
    )
    env = build_env(store, keypair[1])
    resp = env.validate("sig", req)  # no exception
    assert resp.allowed in (True, False)


def test_keyless_entries_fail_settings_validation():
    with pytest.raises(BootstrapFailure, match="keyless"):
        EvaluationEnvironmentBuilder(backend="jax").build(
            {
                "sig": parse_policy_entry(
                    "sig",
                    {
                        "module": "builtin://verify-image-signatures",
                        "settings": {
                            "signatures": [
                                {
                                    "image": "x/*",
                                    "githubActions": {"owner": "kubewarden"},
                                }
                            ]
                        },
                    },
                )
            }
        )


def test_missing_pubkeys_fail_settings_validation():
    with pytest.raises(BootstrapFailure, match="pubKeys"):
        EvaluationEnvironmentBuilder(backend="jax").build(
            {
                "sig": parse_policy_entry(
                    "sig",
                    {
                        "module": "builtin://verify-image-signatures",
                        "settings": {"signatures": [{"image": "x/*"}]},
                    },
                )
            }
        )
