"""Zero-downtime policy lifecycle (lifecycle.py): epoch-based hot reload
with shadow canary and last-good rollback.

The contract under test, end to end:

* a reload builds + warms + canaries the NEW policy set entirely in the
  background; promotion is an atomic epoch-pointer flip and the old
  epoch stays pinned (environment open) for one generation;
* a candidate that fails ANY pipeline stage — fetch, compile,
  settings validation, canary trap/timeout/divergence — never serves a
  single request: last-good keeps serving and the rollback counter
  increments;
* verdict-cache and circuit-breaker state are scoped per epoch (a new
  set can never observe the old set's cached verdicts or trip state);
* rollback revives the pinned epoch instantly (fresh batcher over the
  still-open environment);
* /readiness is honest: 503 before the first epoch, 200 on last-good
  during a background reload, 503 under --degraded-mode reject with
  every shard breaker open.
"""

from __future__ import annotations

import threading
import time

import pytest

from policy_server_tpu import failpoints
from policy_server_tpu.api.service import RequestOrigin
from policy_server_tpu.api.state import ApiServerState
from policy_server_tpu.evaluation.environment import (
    EvaluationEnvironmentBuilder,
)
from policy_server_tpu.lifecycle import (
    PolicyLifecycleManager,
    ReloadRejected,
    ShadowRecorder,
)
from policy_server_tpu.models import (
    AdmissionResponse,
    AdmissionReviewRequest,
    ValidateRequest,
)
from policy_server_tpu.models.policy import parse_policy_entry
from policy_server_tpu.runtime.batcher import MicroBatcher

from conftest import build_admission_review_dict


@pytest.fixture(autouse=True)
def clean_failpoints():
    failpoints.reset()
    yield
    failpoints.reset()


def review(namespace: str | None = None) -> ValidateRequest:
    doc = build_admission_review_dict()
    if namespace is not None:
        doc["request"]["namespace"] = namespace
    return ValidateRequest.from_admission(
        AdmissionReviewRequest.from_dict(doc).request
    )


def policies_v1() -> dict:
    return {
        "ns": parse_policy_entry(
            "ns",
            {
                "module": "builtin://namespace-validate",
                "settings": {"denied_namespaces": ["blocked"]},
            },
        ),
    }


def policies_v2() -> dict:
    out = policies_v1()
    out["happy"] = parse_policy_entry(
        "happy", {"module": "builtin://always-happy"}
    )
    return out


class Harness:
    """A lifecycle manager over REAL jax/oracle environments, wired the
    same way server.py wires it (shared recorder, per-epoch batchers)."""

    def __init__(self, mode: str = "auto", divergence_threshold: float = 0.0,
                 oracle_wrapper=None):
        self.recorder = ShadowRecorder(capacity=16)
        self.built_oracles: list = []
        self._oracle_wrapper = oracle_wrapper

        env0 = self.build_env(policies_v1())
        batcher0 = self.build_batcher(env0)
        batcher0.start()
        self.state = ApiServerState(
            evaluation_environment=env0, batcher=batcher0, ready=False
        )
        self.manager = PolicyLifecycleManager(
            state=self.state,
            build_environment=self.build_env,
            build_oracle_environment=self.build_oracle,
            build_batcher=self.build_batcher,
            recorder=self.recorder,
            mode=mode,
            canary_requests=16,
            divergence_threshold=divergence_threshold,
            warmup=False,  # envs compile lazily on first canary dispatch
        )
        self.state.lifecycle = self.manager
        self.epoch0 = self.manager.install_first_epoch(
            env0, batcher0, policies_v1()
        )

    def build_env(self, policies):
        return EvaluationEnvironmentBuilder(
            backend="jax", verdict_cache_size=0
        ).build(dict(policies))

    def build_oracle(self, policies):
        env = EvaluationEnvironmentBuilder(backend="oracle").build(
            dict(policies)
        )
        if self._oracle_wrapper is not None:
            env = self._oracle_wrapper(env)
        self.built_oracles.append(env)
        return env

    def build_batcher(self, env):
        return MicroBatcher(
            env, max_batch_size=4, batch_timeout_ms=1.0, policy_timeout=5.0,
            host_fastpath_threshold=64, shadow_recorder=self.recorder,
        )

    def serve(self, policy_id: str, namespace: str | None = None):
        return self.state.batcher.submit(
            policy_id, review(namespace), RequestOrigin.VALIDATE
        ).result(timeout=10)

    def close(self):
        self.manager.shutdown()


@pytest.fixture
def harness():
    h = Harness()
    yield h
    h.close()


# ---------------------------------------------------------------------------
# Shadow recorder
# ---------------------------------------------------------------------------


def test_shadow_recorder_ring_is_bounded():
    rec = ShadowRecorder(capacity=4)
    for i in range(10):
        rec.observe([(f"p{i}", object())])
    snap = rec.snapshot()
    assert len(snap) == 4
    assert [pid for pid, _ in snap] == ["p6", "p7", "p8", "p9"]
    assert len(rec) == 4


def test_batcher_feeds_the_recorder(harness):
    assert harness.serve("ns").allowed is True
    assert any(pid == "ns" for pid, _ in harness.recorder.snapshot())


# ---------------------------------------------------------------------------
# Reload pipeline: promote / reject / epoch scoping
# ---------------------------------------------------------------------------


def test_reload_promotes_new_epoch_atomically(harness):
    # old set serves; the new policy does not exist yet
    assert harness.serve("ns", namespace="blocked").allowed is False
    old_env = harness.state.evaluation_environment
    old_batcher = harness.state.batcher

    assert harness.manager.reload(policies=policies_v2()) == "promoted"

    # the epoch pointer flipped: new env + new batcher, new policy serves
    assert harness.state.evaluation_environment is not old_env
    assert harness.state.batcher is not old_batcher
    assert harness.serve("happy").allowed is True
    assert harness.serve("ns", namespace="blocked").allowed is False
    stats = harness.manager.stats()
    assert stats["reloads"] == 1 and stats["epoch"] == 1
    assert stats["reload_failures"] == 0 and stats["rollbacks"] == 0
    assert stats["canary_replays"] > 0

    # epoch scoping: the breaker and cache are the NEW environment's own
    new_env = harness.state.evaluation_environment
    assert new_env.breaker is not old_env.breaker

    # the demoted epoch is PINNED: its environment stays open (rollback
    # target), even after its batcher drain-retires
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline and not old_batcher._stopping:
        time.sleep(0.05)
    assert old_batcher._stopping, "demoted batcher should drain-retire"
    assert not old_env._closed, "pinned epoch env must stay open"


def test_second_promotion_closes_the_epoch_beyond_the_pin(harness):
    env0 = harness.state.evaluation_environment
    harness.manager.reload(policies=policies_v2())
    assert not env0._closed
    harness.manager.reload(policies=policies_v1())
    # epoch 0 fell past the one-generation pin window
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline and not env0._closed:
        time.sleep(0.05)
    assert env0._closed
    # the middle epoch is now pinned and still open
    assert harness.manager.stats()["epoch"] == 2


@pytest.mark.parametrize("site,stage", [
    ("reload.fetch", "fetch"),
    ("reload.compile", "compile"),
    ("reload.canary", "canary"),
])
def test_failed_stage_keeps_last_good_and_counts_rollback(
    harness, site, stage
):
    """A candidate that fails ANY pipeline stage never serves: the
    current epoch is untouched and the rollback counter is loud."""
    failpoints.configure(f"{site}=raise:injected-reload-fault")
    env_before = harness.state.evaluation_environment
    with pytest.raises(ReloadRejected) as exc:
        harness.manager.reload(policies=policies_v2())
    assert exc.value.stage == stage
    assert failpoints.fired_count(site) == 1
    # last-good serving, bit-exact
    assert harness.state.evaluation_environment is env_before
    assert harness.serve("ns").allowed is True
    assert harness.serve("ns", namespace="blocked").allowed is False
    with pytest.raises(Exception):
        harness.serve("happy")  # the rejected set never served
    stats = harness.manager.stats()
    assert stats["reload_failures"] == 1
    assert stats["rollbacks"] == 1
    assert stats["reloads"] == 0 and stats["epoch"] == 0


def test_settings_validation_failure_rejects_at_compile(harness):
    bad = {
        "ns": parse_policy_entry(
            "ns",
            {
                "module": "builtin://namespace-validate",
                # denied_namespaces must be a list — settings validation
                # rejects this before any program is built
                "settings": {"denied_namespaces": 17},
            },
        )
    }
    with pytest.raises(ReloadRejected) as exc:
        harness.manager.reload(policies=bad)
    assert exc.value.stage == "compile"
    assert harness.serve("ns").allowed is True
    assert harness.manager.stats()["rollbacks"] == 1


# ---------------------------------------------------------------------------
# Shadow canary: divergence, threshold, timeout
# ---------------------------------------------------------------------------


class _FlippingOracle:
    """An oracle whose every verdict disagrees with the candidate —
    the worst possible policy push."""

    def __init__(self, inner):
        self._inner = inner

    def validate_batch(self, pairs, run_hooks=True, prefer_host=False):
        out = self._inner.validate_batch(pairs, run_hooks=run_hooks)
        flipped = []
        for r in out:
            if isinstance(r, Exception):
                flipped.append(r)
            else:
                flipped.append(
                    AdmissionResponse(uid=r.uid, allowed=not r.allowed)
                )
        return flipped

    def close(self):
        self._inner.close()


def test_canary_divergence_rejects_candidate():
    h = Harness(oracle_wrapper=_FlippingOracle)
    try:
        with pytest.raises(ReloadRejected) as exc:
            h.manager.reload(policies=policies_v2())
        assert exc.value.stage == "canary"
        assert "divergence" in str(exc.value)
        stats = h.manager.stats()
        assert stats["canary_divergences"] > 0
        assert stats["rollbacks"] == 1 and stats["epoch"] == 0
        # last-good serving
        assert h.serve("ns").allowed is True
    finally:
        h.close()


def test_divergence_threshold_tolerates_configured_fraction():
    """threshold=1.0 admits any divergence level — the operator's knob
    for sets that intentionally change verdicts."""
    h = Harness(oracle_wrapper=_FlippingOracle, divergence_threshold=1.0)
    try:
        assert h.manager.reload(policies=policies_v2()) == "promoted"
        assert h.manager.stats()["canary_divergences"] > 0
        assert h.serve("happy").allowed is True
    finally:
        h.close()


def test_hung_canary_rejects_by_timeout(harness):
    harness.manager.canary_timeout_seconds = 0.3
    failpoints.set_failpoint("reload.canary", lambda: time.sleep(5))
    t0 = time.perf_counter()
    with pytest.raises(ReloadRejected) as exc:
        harness.manager.reload(policies=policies_v2())
    assert exc.value.stage == "canary"
    assert time.perf_counter() - t0 < 4.0
    assert harness.serve("ns").allowed is True


def test_slow_oracle_replay_rejected_by_timeout():
    """The timeout bounds the WHOLE replay (candidate and oracle side):
    a wedged comparison can never gate promotion forever."""
    h = Harness()
    try:
        h.manager.canary_timeout_seconds = 0.3

        real_validate = {}

        def slow_oracle(env):
            real = env.validate_batch

            def slow(pairs, run_hooks=True, prefer_host=False):
                time.sleep(5)
                return real(pairs, run_hooks=run_hooks)

            env.validate_batch = slow
            real_validate["fn"] = real
            return env

        h._oracle_wrapper = slow_oracle
        with pytest.raises(ReloadRejected) as exc:
            h.manager.reload(policies=policies_v2())
        assert exc.value.stage == "canary"
        assert "hung candidate" in str(exc.value)
    finally:
        h.close()


# ---------------------------------------------------------------------------
# Manual mode + rollback
# ---------------------------------------------------------------------------


def test_manual_mode_stages_then_promotes():
    h = Harness(mode="manual")
    try:
        assert h.manager.reload(policies=policies_v2()) == "staged"
        # staged ≠ serving: the new policy is not reachable yet
        with pytest.raises(Exception):
            h.serve("happy")
        assert h.manager.stats()["staged"] == 1
        assert h.manager.stats()["epoch"] == 0
        assert h.manager.promote_staged() == "promoted"
        assert h.serve("happy").allowed is True
        assert h.manager.stats()["epoch"] == 1
        # nothing staged anymore
        with pytest.raises(ReloadRejected):
            h.manager.promote_staged()
    finally:
        h.close()


def test_rollback_restores_previous_epoch(harness):
    harness.manager.reload(policies=policies_v2())
    assert harness.serve("happy").allowed is True
    assert harness.manager.rollback() == "rolled-back"
    # back on the v1 set: happy is gone, ns still bit-exact
    with pytest.raises(Exception):
        harness.serve("happy")
    assert harness.serve("ns", namespace="blocked").allowed is False
    stats = harness.manager.stats()
    assert stats["rollbacks"] == 1 and stats["epoch"] == 0
    # symmetric: the demoted (v2) epoch is pinned — roll forward again
    assert harness.manager.rollback() == "rolled-back"
    assert harness.serve("happy").allowed is True


def test_rollback_without_previous_epoch_rejects(harness):
    with pytest.raises(ReloadRejected):
        harness.manager.rollback()


def test_request_reload_coalesces(harness):
    """Concurrent triggers coalesce onto one in-flight reload."""
    release = __import__("threading").Event()
    failpoints.set_failpoint("reload.fetch", lambda: release.wait(10))
    try:
        assert harness.manager.request_reload("t1") is True
        time.sleep(0.1)
        assert harness.manager.request_reload("t2") is False
    finally:
        release.set()
    deadline = time.monotonic() + 10
    while (
        time.monotonic() < deadline
        and harness.manager.stats()["reloads"] == 0
    ):
        time.sleep(0.05)
    assert harness.manager.stats()["reloads"] == 1


# ---------------------------------------------------------------------------
# File-watch trigger
# ---------------------------------------------------------------------------


def test_policies_file_watch_triggers_reload(tmp_path, monkeypatch):
    import yaml

    from policy_server_tpu import lifecycle as lifecycle_mod
    from policy_server_tpu.config.config import read_policies_file

    monkeypatch.setattr(lifecycle_mod, "WATCH_INTERVAL_SECONDS", 0.05)
    path = tmp_path / "policies.yml"
    path.write_text(yaml.safe_dump(
        {"ns": {"module": "builtin://namespace-validate",
                "settings": {"denied_namespaces": ["blocked"]}}}
    ))

    h = Harness()
    try:
        h.manager._read_policies = lambda: read_policies_file(path)
        h.manager._policies_path = str(path)
        h.manager.start_watching()
        time.sleep(0.2)  # watcher sees the initial digest
        path.write_text(yaml.safe_dump(
            {"ns": {"module": "builtin://namespace-validate",
                    "settings": {"denied_namespaces": ["blocked"]}},
             "happy": {"module": "builtin://always-happy"}}
        ))
        deadline = time.monotonic() + 15
        while (
            time.monotonic() < deadline
            and h.manager.stats()["reloads"] == 0
        ):
            time.sleep(0.05)
        assert h.manager.stats()["reloads"] == 1
        assert h.serve("happy").allowed is True
    finally:
        h.close()


# ---------------------------------------------------------------------------
# Readiness honesty (ApiServerState.readiness)
# ---------------------------------------------------------------------------


class _FakeEnv:
    breaker_all_open = False

    def close(self):
        pass


class _FakeBatcher:
    degraded_mode = "oracle"

    def shutdown(self):
        pass


def test_readiness_honest_states():
    env, batcher = _FakeEnv(), _FakeBatcher()
    state = ApiServerState(
        evaluation_environment=env, batcher=batcher, ready=False
    )
    assert state.readiness()[0] == 503  # first epoch not warmed yet
    state.ready = True
    assert state.readiness()[0] == 200
    # degraded reject + every breaker open: the server would 503 every
    # review, so readiness must say so
    batcher.degraded_mode = "reject"
    env.breaker_all_open = True
    assert state.readiness()[0] == 503
    # oracle mode keeps serving bit-exact host verdicts → still ready
    batcher.degraded_mode = "oracle"
    assert state.readiness()[0] == 200


def test_readiness_stays_200_on_last_good_during_background_reload(harness):
    """A background reload (even one that eventually fails) must not
    un-ready the server: last-good serves throughout."""
    harness.state.ready = True
    release = __import__("threading").Event()
    failpoints.set_failpoint("reload.compile", lambda: release.wait(10))
    try:
        assert harness.manager.request_reload("bg") is True
        time.sleep(0.1)  # reload parked mid-compile
        assert harness.state.readiness()[0] == 200
        assert harness.serve("ns").allowed is True
    finally:
        release.set()


def test_default_auto_mode_wires_lifecycle_into_server_config():
    """Config defaults: hot reload on (auto), canary budget present, no
    admin token (endpoints disabled), programmatic configs carry no
    policies path (no watcher)."""
    from policy_server_tpu.config.config import Config, TlsConfig

    cfg = Config(policies={}, tls_config=TlsConfig())
    assert cfg.policy_reload_mode == "auto"
    assert cfg.reload_canary_requests == 64
    assert cfg.reload_divergence_threshold == 0.0
    assert cfg.reload_admin_token is None
    assert cfg.policies_path is None
    cfg.validate()
    cfg.policy_reload_mode = "sometimes"
    with pytest.raises(ValueError):
        cfg.validate()


# ---------------------------------------------------------------------------
# Review-hardening regressions (round 9)
# ---------------------------------------------------------------------------


def test_hung_canary_does_not_poison_the_next_reload(harness):
    """A canary abandoned at its timeout runs on a throwaway daemon
    thread: the NEXT reload gets a fresh one and must promote cleanly
    (a fixed one-worker pool would stay wedged behind the hung replay
    and time out every future canary)."""
    harness.manager.canary_timeout_seconds = 0.3
    failpoints.set_failpoint(
        "reload.canary", lambda: time.sleep(5), count=1
    )
    with pytest.raises(ReloadRejected):
        harness.manager.reload(policies=policies_v2())
    # Fault exhausted: the very next reload must succeed. The timeout is
    # restored to 4 s first — the harness builds candidates warmup=False,
    # so this canary pays a cold jit compile that 0.3 s cannot absorb on
    # a loaded box (the old value made the test flake on compile time,
    # not on the property under test). 4 s still distinguishes the
    # regression this guards: a wedged one-worker pool would sit behind
    # the ~4.7 s remaining of the abandoned replay's sleep and time out.
    harness.manager.canary_timeout_seconds = 4.0
    assert harness.manager.reload(policies=policies_v2()) == "promoted"
    assert harness.serve("happy").allowed is True


def test_rollback_answers_409_during_inflight_reload(harness):
    """The emergency endpoints never hang behind a compile: a rollback
    racing an in-flight reload gets a bounded-wait rejection (HTTP 409)
    instead of blocking for the whole pipeline."""
    import threading as _threading

    harness.manager._ADMIN_LOCK_TIMEOUT_SECONDS = 0.2
    release = _threading.Event()
    failpoints.set_failpoint("reload.compile", lambda: release.wait(10))
    try:
        assert harness.manager.request_reload("bg") is True
        time.sleep(0.1)  # the reload holds _reload_lock mid-compile
        with pytest.raises(ReloadRejected, match="in progress"):
            harness.manager.rollback()
    finally:
        release.set()


def test_corpus_synthetics_are_never_capped(harness):
    """Every policy in the candidate set gets at least one canary
    replay, regardless of --reload-canary-requests; the cap bounds only
    the recorded-traffic portion (and 0 disables recorded replay, not
    the cap)."""
    for i in range(10):
        harness.recorder.observe([("ns", review())])
    harness.manager.canary_requests = 2
    many = {
        f"p{i}": parse_policy_entry(
            f"p{i}", {"module": "builtin://always-happy"}
        )
        for i in range(5)
    }
    corpus = harness.manager._corpus(many)
    assert len(corpus) == 2 + 5  # 2 recorded (capped) + one per policy
    assert [pid for pid, _ in corpus[:2]] == ["ns", "ns"]
    assert {pid for pid, _ in corpus[2:]} == set(many)

    harness.manager.canary_requests = 0
    corpus = harness.manager._corpus(many)
    assert {pid for pid, _ in corpus} == set(many)  # synthetics only


def test_file_watch_redetects_change_landing_during_inflight_reload(
    tmp_path, monkeypatch
):
    """A policies.yml write landing while a reload is already in flight
    must not be lost: the watcher re-detects it once the running reload
    settles (the digest baseline only advances when a trigger lands)."""
    import threading as _threading

    import yaml

    from policy_server_tpu import lifecycle as lifecycle_mod
    from policy_server_tpu.config.config import read_policies_file

    monkeypatch.setattr(lifecycle_mod, "WATCH_INTERVAL_SECONDS", 0.05)
    path = tmp_path / "policies.yml"
    path.write_text(yaml.safe_dump(
        {"ns": {"module": "builtin://namespace-validate",
                "settings": {"denied_namespaces": ["blocked"]}}}
    ))
    h = Harness()
    try:
        h.manager._read_policies = lambda: read_policies_file(path)
        h.manager._policies_path = str(path)
        h.manager.start_watching()
        time.sleep(0.2)

        # park a reload mid-compile, then write the REAL change
        release = _threading.Event()
        failpoints.set_failpoint(
            "reload.compile", lambda: release.wait(15), count=1
        )
        assert h.manager.request_reload("occupant") is True
        time.sleep(0.1)
        path.write_text(yaml.safe_dump(
            {"ns": {"module": "builtin://namespace-validate",
                    "settings": {"denied_namespaces": ["blocked"]}},
             "happy": {"module": "builtin://always-happy"}}
        ))
        time.sleep(0.3)  # watcher ticks see the change but cannot land it
        release.set()  # the occupant reload finishes (old content)
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline:
            try:
                if h.serve("happy").allowed is True:
                    break
            except Exception:
                time.sleep(0.1)
        assert h.serve("happy").allowed is True, (
            "the change written during the in-flight reload was lost"
        )
    finally:
        h.close()


# ---------------------------------------------------------------------------
# Multi-tenant epoch isolation (round 16, tenancy.py): tenants are
# independent lifecycle managers over their own TenantState — one
# tenant's reload/rollback/ring can never touch another's.
# ---------------------------------------------------------------------------


class TenantHarness:
    """Two Harness-shaped stacks keyed by tenant name, each a
    PolicyLifecycleManager over its own TenantState (exactly how
    server.py wires named tenants)."""

    def __init__(self):
        from policy_server_tpu.tenancy import TenantState

        self.tenants: dict[str, PolicyLifecycleManager] = {}
        self.states: dict[str, TenantState] = {}
        self.recorders: dict[str, ShadowRecorder] = {}
        for name in ("ten-a", "ten-b"):
            recorder = ShadowRecorder(capacity=16)
            env = EvaluationEnvironmentBuilder(backend="jax").build(
                policies_v1()
            )
            batcher = MicroBatcher(
                env, max_batch_size=4, batch_timeout_ms=1.0,
                policy_timeout=5.0, host_fastpath_threshold=64,
                shadow_recorder=recorder, tenant=name,
            ).start()
            state = TenantState(name=name)
            manager = PolicyLifecycleManager(
                state=state,
                build_environment=lambda p: (
                    EvaluationEnvironmentBuilder(backend="jax").build(dict(p))
                ),
                build_oracle_environment=lambda p: (
                    EvaluationEnvironmentBuilder(backend="oracle").build(
                        dict(p)
                    )
                ),
                build_batcher=lambda env, _r=recorder, _n=name: MicroBatcher(
                    env, max_batch_size=4, batch_timeout_ms=1.0,
                    policy_timeout=5.0, host_fastpath_threshold=64,
                    shadow_recorder=_r, tenant=_n,
                ),
                recorder=recorder,
                warmup=False,
                tenant=name,
            )
            state.lifecycle = manager
            manager.install_first_epoch(env, batcher, policies_v1())
            self.tenants[name] = manager
            self.states[name] = state
            self.recorders[name] = recorder

    def serve(self, tenant: str, policy_id: str, namespace=None):
        return self.states[tenant].batcher.submit(
            policy_id, review(namespace), RequestOrigin.VALIDATE
        ).result(timeout=10)

    def close(self):
        for m in self.tenants.values():
            m.shutdown()


def test_tenant_reloads_promote_independent_epochs():
    """Concurrent reloads on two tenants each advance THEIR epoch only;
    verdict caches and canary rings stay tenant-scoped."""
    h = TenantHarness()
    try:
        # seed distinct traffic into each tenant's canary ring
        assert h.serve("ten-a", "ns").allowed is True
        assert h.serve("ten-b", "ns", namespace="blocked").allowed is False

        threads = [
            threading.Thread(
                target=h.tenants[n].reload,
                kwargs=dict(policies=policies_v2(), reason="test"),
            )
            for n in ("ten-a", "ten-b")
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        assert h.tenants["ten-a"].current_epoch == 1
        assert h.tenants["ten-b"].current_epoch == 1
        # each tenant serves ITS promoted set
        assert h.serve("ten-a", "happy").allowed is True
        assert h.serve("ten-b", "happy").allowed is True
        # per-tenant rollback reverts only that tenant
        assert h.tenants["ten-a"].rollback() == "rolled-back"
        assert h.tenants["ten-a"].current_epoch == 0
        assert h.tenants["ten-b"].current_epoch == 1
        assert h.serve("ten-b", "happy").allowed is True
    finally:
        h.close()


def test_tenant_scoped_canary_fault_rolls_back_one_tenant():
    """A reload.canary fault scoped to tenant A rejects A's candidate
    (last-good keeps serving, rollback counter increments) while tenant
    B's SAME reload promotes — the per-tenant containment contract."""
    h = TenantHarness()
    try:
        def boom():
            raise failpoints.FailpointError("canary infrastructure down")

        failpoints.set_failpoint("reload.canary", boom, scope="ten-a")
        with pytest.raises(ReloadRejected):
            h.tenants["ten-a"].reload(policies=policies_v2(), reason="x")
        assert h.tenants["ten-b"].reload(
            policies=policies_v2(), reason="x"
        ) == "promoted"
        a_stats = h.tenants["ten-a"].stats()
        b_stats = h.tenants["ten-b"].stats()
        assert a_stats["epoch"] == 0 and a_stats["rollbacks"] == 1
        assert b_stats["epoch"] == 1 and b_stats["rollbacks"] == 0
        # A still serves last-good; B serves the new set
        assert h.serve("ten-a", "ns").allowed is True
        assert h.serve("ten-b", "happy").allowed is True
    finally:
        h.close()


def test_tenant_canary_rings_do_not_cross():
    h = TenantHarness()
    try:
        assert h.serve("ten-a", "ns").allowed is True
        ring_a = h.recorders["ten-a"].snapshot()
        ring_b = h.recorders["ten-b"].snapshot()
        assert len(ring_a) >= 1
        assert ring_b == []  # B never saw A's traffic
    finally:
        h.close()
