"""Tests for tools/fuzz_native.py — the fuzzer is the artifact under
test here, not the parser.

The capstone is the round-19 rediscovery pin: build a variant
httpfront.so with the round-19 parse_verdict_record bounds fixes
surgically reverted and prove the fuzzer's shared corpus crashes it
(nonzero subprocess exit) while the real library survives the same run.
If the fuzzer ever rots to where it cannot rediscover a bug we already
shipped a fix for, this fails before `make sanitize` reports a
meaningless green.
"""

from __future__ import annotations

import shutil
import subprocess
import sys
from pathlib import Path

import pytest

from policy_server_tpu.runtime import native_frontend as nf
from tools.fuzz_native import (
    Mutator,
    http_corpus,
    tls_corpus,
    verdict_record_corpus,
)

REPO_ROOT = Path(__file__).resolve().parents[1]
CSRC = REPO_ROOT / "csrc" / "httpfront.cpp"

# the round-19 bounds fixes, verbatim — reverting THESE lines is the
# rediscovery experiment. If either anchor drifts, fail loudly: the
# test must be re-pinned to the moved guard, never silently skipped.
R19_GUARDS = (
    "    if ((int64_t)wlen > len - off) return false;\n",
    "    if ((int64_t)n_causes * 8 > len - off) return false;"
    "  // 8 B/cause min\n",
)


def _fuzz(*argv: str, timeout: int = 120) -> subprocess.CompletedProcess:
    return subprocess.run(
        [sys.executable, "-m", "tools.fuzz_native", *argv],
        cwd=REPO_ROOT, capture_output=True, text=True, timeout=timeout,
    )


def test_corpus_carries_the_r19_regressions():
    corpus = verdict_record_corpus()
    names = [n for n, _, _ in corpus]
    assert len(names) == len(set(names)), "duplicate corpus names"
    rejects = {n for n, _, e in corpus if e == "reject"}
    assert rejects >= {
        "r19-warnlen-topbit", "r19-warnlen-oversize",
        "r19-causes-giant", "r19-truncated",
    }
    # both accept and reject seeds present: the fuzzer mutates from
    # valid structure, the unit tests assert exact verdicts
    assert any(e == "accept" for _, _, e in corpus)
    assert all(isinstance(d, bytes) and d for _, d, _ in corpus)


def test_mutator_is_deterministic():
    seeds = [d for _, d, _ in verdict_record_corpus()]
    a = Mutator(42)
    b = Mutator(42)
    out_a = [a.mutate(s) for s in seeds * 20]
    out_b = [b.mutate(s) for s in seeds * 20]
    assert out_a == out_b
    # a different seed takes a different path (sanity, not a guarantee
    # for every pair — 42/43 are pinned known-divergent)
    c = Mutator(43)
    assert [c.mutate(s) for s in seeds * 20] != out_a


def test_http_and_tls_corpora_shape():
    http = http_corpus()
    assert {n for n, _ in http} >= {
        "content-length", "chunked-trailers", "pipelined", "oversize-decl",
    }
    assert all(isinstance(d, bytes) and d for _, d in http)
    tls = tls_corpus()
    hello = dict(tls)["client-hello"]
    assert hello[:1] == b"\x16", "ClientHello must be a TLS handshake record"


@pytest.mark.skipif(not nf.native_available(), reason="native frontend unavailable")
def test_fuzzer_clean_on_real_library():
    r = _fuzz("--target", "records", "--time-budget", "2", "--seed", "7")
    assert r.returncode == 0, r.stdout + r.stderr
    assert "no crash" in r.stdout


@pytest.mark.skipif(
    shutil.which("g++") is None, reason="g++ unavailable to build the variant"
)
def test_fuzzer_rediscovers_r19_bounds_bug(tmp_path):
    src = CSRC.read_text()
    for guard in R19_GUARDS:
        if guard not in src:
            pytest.fail(
                "round-19 guard anchor not found in csrc/httpfront.cpp — "
                f"re-pin R19_GUARDS to the moved bounds check: {guard!r}"
            )
        src = src.replace(guard, "")
    variant_src = tmp_path / "httpfront_r19_reverted.cpp"
    variant_src.write_text(src)
    variant_so = tmp_path / "httpfront_r19_reverted.so"
    build = subprocess.run(
        ["g++", "-O0", "-shared", "-fPIC", "-std=c++17", "-pthread",
         str(variant_src), "-o", str(variant_so), "-ldl"],
        capture_output=True, text=True, timeout=300,
    )
    assert build.returncode == 0, build.stderr[-2000:]

    # the reverted variant must CRASH under the shared corpus (the
    # unmutated round-19 seeds alone rediscover the bug)
    bad = _fuzz(
        "--target", "records", "--lib", str(variant_so),
        "--time-budget", "5", "--seed", "7",
    )
    assert bad.returncode != 0, (
        "fuzzer failed to rediscover the round-19 parse_verdict_record "
        "bounds bug in the reverted variant:\n" + bad.stdout + bad.stderr
    )

    # and the same run against the REAL library survives
    if nf.native_available():
        good = _fuzz("--target", "records", "--time-budget", "5", "--seed", "7")
        assert good.returncode == 0, good.stdout + good.stderr
