"""Micro-batcher tests: batched verdicts match the single-request path,
mixed-policy batching, deadline protection (the reference's sleeping-policy
timeout tests, tests/integration_test.rs:367-423), and overload behavior."""

from __future__ import annotations

import threading

import pytest

from policy_server_tpu.api.service import RequestOrigin, evaluate
from policy_server_tpu.evaluation.environment import (
    EvaluationEnvironmentBuilder,
    bucket_size,
)
from policy_server_tpu.models import AdmissionReviewRequest, ValidateRequest
from policy_server_tpu.models.policy import parse_policy_entry
from policy_server_tpu.runtime.batcher import DEADLINE_MESSAGE, MicroBatcher
from policy_server_tpu.telemetry import metrics as metrics_mod

from conftest import build_admission_review_dict


@pytest.fixture(autouse=True)
def fresh_metrics():
    metrics_mod.reset_metrics_for_tests()
    yield
    metrics_mod.reset_metrics_for_tests()


def pod_review(namespace: str, privileged: bool) -> ValidateRequest:
    doc = build_admission_review_dict()
    doc["request"]["namespace"] = namespace
    doc["request"]["object"] = {
        "apiVersion": "v1",
        "kind": "Pod",
        "metadata": {"name": "p", "namespace": namespace},
        "spec": {
            "containers": [
                {
                    "name": "c",
                    "image": "nginx",
                    "securityContext": {"privileged": privileged},
                }
            ]
        },
    }
    return ValidateRequest.from_admission(
        AdmissionReviewRequest.from_dict(doc).request
    )


@pytest.fixture(scope="module")
def env():
    policies = {
        "priv": parse_policy_entry("priv", {"module": "builtin://pod-privileged"}),
        "ns": parse_policy_entry(
            "ns",
            {
                "module": "builtin://namespace-validate",
                "settings": {"denied_namespaces": ["blocked"]},
            },
        ),
        "grp": parse_policy_entry(
            "grp",
            {
                "expression": "happy() || priv()",
                "message": "group denied",
                "policies": {
                    "happy": {"module": "builtin://always-happy"},
                    "priv": {"module": "builtin://pod-privileged"},
                },
            },
        ),
    }
    return EvaluationEnvironmentBuilder(backend="jax").build(policies)


def test_warmup_rtt_seed_normalized_by_warmup_dispatches():
    """ADVICE r5 #4: the warmup RTT seed divides by the environment's own
    per-warmup dispatch count (schemas × shards for the sharded
    evaluator), not by a schemas attribute the evaluator may not expose —
    the old code overestimated per-dispatch RTT by shards×schemas and
    biased early routing host-side."""
    import time as _time

    class FakeShardedEnv:
        """Duck-typed evaluator: warmup costs a fixed wall per dispatch,
        exposes warmup_dispatches like PolicyShardedEvaluator (no
        ``schemas`` attribute, like the real sharded evaluator)."""

        supports_host_fastpath = True
        warmup_dispatches = 6  # e.g. 3 shards × 2 schemas
        PER_DISPATCH_S = 0.01

        def warmup(self, batch_sizes=(1,)):
            _time.sleep(self.PER_DISPATCH_S * self.warmup_dispatches)

    env = FakeShardedEnv()
    batcher = MicroBatcher(
        env, max_batch_size=2, latency_budget_ms=50.0, policy_timeout=2.0
    )
    batcher.warmup()
    for bucket, rtt in batcher._dev_rtt.items():
        # the seed must approximate ONE dispatch (~10 ms), not the whole
        # shards×schemas warmup sweep (~60 ms)
        assert rtt < 3 * env.PER_DISPATCH_S, (bucket, rtt)
        assert rtt > 0


def test_sharded_evaluator_exposes_warmup_dispatches():
    from policy_server_tpu.evaluation.environment import (
        EvaluationEnvironment,
    )

    # one fused environment: one dispatch per schema per warmup call
    assert EvaluationEnvironment.warmup_dispatches.fget is not None


def test_bucket_size():
    assert [bucket_size(n) for n in (1, 2, 3, 5, 8, 9, 128)] == [
        1, 2, 4, 8, 8, 16, 128,
    ]


def test_batched_matches_single_path(env):
    batcher = MicroBatcher(env, host_fastpath_threshold=0, max_batch_size=16, batch_timeout_ms=5.0).start()
    try:
        cases = [
            ("priv", pod_review("default", True)),
            ("priv", pod_review("default", False)),
            ("ns", pod_review("blocked", False)),
            ("ns", pod_review("ok", False)),
            ("grp", pod_review("default", True)),
            ("grp", pod_review("default", False)),
        ]
        futures = [
            batcher.submit(pid, req, RequestOrigin.VALIDATE) for pid, req in cases
        ]
        batched = [f.result(timeout=30) for f in futures]
        single = [
            evaluate(env, pid, req, RequestOrigin.VALIDATE) for pid, req in cases
        ]
        for b, s in zip(batched, single):
            assert b.to_dict() == s.to_dict()
        # requests for different policies coalesced into few dispatches
        assert batcher.batches_dispatched <= 2
    finally:
        batcher.shutdown()


def test_concurrent_submissions_form_batches(env):
    batcher = MicroBatcher(env, host_fastpath_threshold=0, max_batch_size=32, batch_timeout_ms=20.0).start()
    try:
        results = [None] * 24
        def worker(i: int) -> None:
            req = pod_review("default", i % 2 == 0)
            results[i] = batcher.evaluate("priv", req, RequestOrigin.VALIDATE, timeout=30)
        threads = [threading.Thread(target=worker, args=(i,)) for i in range(24)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        for i, resp in enumerate(results):
            assert resp.allowed == (i % 2 != 0)
        assert batcher.requests_dispatched == 24
        assert batcher.batches_dispatched < 24  # actually batched
    finally:
        batcher.shutdown()


def test_deadline_protection_sleeping_policy():
    """integration_test.rs:367-423: 100 ms sleep passes, long sleep exceeds
    the deadline and rejects in-band with 'execution deadline exceeded'."""
    env = EvaluationEnvironmentBuilder(backend="jax").build(
        {
            "sleep-ok": parse_policy_entry(
                "sleep-ok",
                {"module": "builtin://sleeping", "settings": {"sleep_ms": 100}},
            ),
            "sleep-long": parse_policy_entry(
                "sleep-long",
                {"module": "builtin://sleeping", "settings": {"sleep_ms": 4000}},
            ),
        }
    )
    # warm the fused program OUTSIDE the deadline: this test times the
    # sleeping HOOK against the deadline, and on a loaded CPU box a cold
    # first-dispatch compile alone can (correctly, but irrelevantly here)
    # blow the 0.5 s budget — it flaked ~1-in-3 under the full suite
    env.warmup((1, 4))
    batcher = MicroBatcher(
        env, host_fastpath_threshold=0,
        max_batch_size=4, batch_timeout_ms=1.0, policy_timeout=0.5
    ).start()
    try:
        ok = batcher.evaluate(
            "sleep-ok", pod_review("default", False), RequestOrigin.VALIDATE,
            timeout=30,
        )
        assert ok.allowed
        slow = batcher.evaluate(
            "sleep-long", pod_review("default", False), RequestOrigin.VALIDATE,
            timeout=30,
        )
        assert not slow.allowed
        assert slow.status.message == DEADLINE_MESSAGE
        assert slow.status.code == 500
    finally:
        batcher.shutdown()


def test_unknown_policy_raises_through_future(env):
    batcher = MicroBatcher(env, host_fastpath_threshold=0, max_batch_size=4, batch_timeout_ms=1.0).start()
    try:
        from policy_server_tpu.evaluation.errors import PolicyNotFoundError

        fut = batcher.submit(
            "missing", pod_review("default", False), RequestOrigin.VALIDATE
        )
        with pytest.raises(PolicyNotFoundError):
            fut.result(timeout=30)
    finally:
        batcher.shutdown()


def test_overload_waits_then_rejects_in_band(env):
    """Queue-full behavior is a bounded WAIT (the reference waits on its
    semaphore, handlers.rs:262-266), then an in-band 429 — not an instant
    fast-reject that would fail closed on absorbable bursts."""
    import time as time_mod

    batcher = MicroBatcher(
        env, host_fastpath_threshold=0,
        max_batch_size=1, batch_timeout_ms=0.0,
        queue_capacity=1, policy_timeout=0.3,
    )
    # not started: the queue fills immediately
    first = batcher.submit("priv", pod_review("d", False), RequestOrigin.VALIDATE)
    t0 = time_mod.perf_counter()
    second = batcher.submit("priv", pod_review("d", False), RequestOrigin.VALIDATE)
    waited = time_mod.perf_counter() - t0
    assert waited >= 0.25, f"rejected without waiting ({waited:.3f}s)"
    assert not first.done()
    resp = second.result(timeout=1)
    assert not resp.allowed and resp.status.code == 429
    batcher.shutdown()


def test_overload_burst_absorbed_when_space_frees(env):
    """A submit that hits a momentarily-full queue succeeds once the
    dispatcher drains it (no spurious 429)."""
    batcher = MicroBatcher(
        env, host_fastpath_threshold=0,
        max_batch_size=1, batch_timeout_ms=0.0,
        queue_capacity=1, policy_timeout=2.0,
    )
    first = batcher.submit("priv", pod_review("d", False), RequestOrigin.VALIDATE)
    import threading as threading_mod

    started = threading_mod.Timer(0.05, batcher.start)
    started.start()
    # queue is full; the dispatcher starts 50ms in and drains it
    second = batcher.submit("priv", pod_review("d", True), RequestOrigin.VALIDATE)
    try:
        assert first.result(timeout=30).allowed is True
        assert second.result(timeout=30).allowed is False  # privileged
    finally:
        started.join()
        batcher.shutdown()


def test_submit_async_waits_without_blocking_loop(env):
    """submit_async polls for space on the event loop; a full queue that
    never drains resolves to 429 at the deadline."""
    import asyncio

    batcher = MicroBatcher(
        env, host_fastpath_threshold=0,
        max_batch_size=1, batch_timeout_ms=0.0,
        queue_capacity=1, policy_timeout=0.2,
    )
    batcher.submit("priv", pod_review("d", False), RequestOrigin.VALIDATE)

    async def go():
        fut = await batcher.submit_async(
            "priv", pod_review("d", False), RequestOrigin.VALIDATE
        )
        return await fut

    resp = asyncio.run(go())
    assert not resp.allowed and resp.status.code == 429
    batcher.shutdown()


def test_shutdown_does_not_close_shared_environment(env):
    """Regression (round-2 VERDICT weak #1): the batcher borrows its
    environment; shutting one batcher down must leave the env — and any
    other batcher sharing it — fully functional."""
    a = MicroBatcher(env, host_fastpath_threshold=0, max_batch_size=4, batch_timeout_ms=1.0).start()
    b = MicroBatcher(env, host_fastpath_threshold=0, max_batch_size=4, batch_timeout_ms=1.0).start()
    try:
        assert a.evaluate(
            "priv", pod_review("d", False), RequestOrigin.VALIDATE, timeout=30
        ).allowed
    finally:
        a.shutdown()
    # direct env path still works after a's shutdown
    (direct,) = env.validate_batch([("priv", pod_review("d", True))])
    assert direct.allowed is False
    # and so does the surviving batcher
    try:
        assert b.evaluate(
            "priv", pod_review("d", False), RequestOrigin.VALIDATE, timeout=30
        ).allowed
    finally:
        b.shutdown()


def test_closed_environment_fails_loudly():
    """A closed environment raises RuntimeError('environment closed') at the
    dispatch entry instead of AttributeError deep in the batch path."""
    owned = EvaluationEnvironmentBuilder(backend="jax").build(
        {"priv": parse_policy_entry("priv", {"module": "builtin://pod-privileged"})}
    )
    (ok,) = owned.validate_batch([("priv", pod_review("d", False))])
    assert ok.allowed
    owned.close()
    owned.close()  # idempotent
    with pytest.raises(RuntimeError, match="environment closed"):
        owned.validate_batch([("priv", pod_review("d", False))])


def test_shutdown_resolves_overload_waiters(env):
    """Regression (round-2 ADVICE medium): submit_async waiters parked on a
    full queue must all resolve during shutdown — none may strand an
    unresolved future after the drain empties the queue."""
    import asyncio

    batcher = MicroBatcher(
        env, host_fastpath_threshold=0,
        max_batch_size=1, batch_timeout_ms=0.0,
        queue_capacity=1, policy_timeout=None,  # unbounded waiters
    )
    # not started: queue fills and stays full
    batcher.submit("priv", pod_review("d", False), RequestOrigin.VALIDATE)

    async def go():
        futs = [
            await batcher.submit_async(
                "priv", pod_review("d", False), RequestOrigin.VALIDATE
            )
            for _ in range(12)  # > overload pool width of 8
        ]
        await asyncio.get_running_loop().run_in_executor(None, batcher.shutdown)
        return await asyncio.gather(*futs)

    responses = asyncio.run(asyncio.wait_for(go(), timeout=30))
    assert len(responses) == 12
    for r in responses:
        assert not r.allowed and r.status.code == 503
    # post-shutdown submissions reject immediately instead of hanging
    late = batcher.submit("priv", pod_review("d", False), RequestOrigin.VALIDATE)
    assert late.result(timeout=1).status.code == 503


def test_budget_routing_keeps_latency_under_budget(env):
    """Deadline-aware routing (VERDICT r4 #2): when the measured device
    round-trip would blow a request's latency budget and the host path
    would not, the batch is answered host-side. Mixed load against an
    artificially slow device: after the router learns the device RTT, no
    request the host path could serve waits past its budget."""
    import time

    SLOW_DEVICE_S = 0.25
    BUDGET_S = 0.10

    class SlowDeviceEnv:
        """Env proxy: device dispatches cost SLOW_DEVICE_S; the host
        fast-path answers at real host speed."""

        def __init__(self, inner):
            self._inner = inner
            self.device_batches = 0
            self.host_batches = 0

        def __getattr__(self, name):
            return getattr(self._inner, name)

        def validate_batch(self, items, run_hooks=True, prefer_host=False):
            if prefer_host:
                self.host_batches += 1
                return self._inner.validate_batch(
                    items, run_hooks=run_hooks, prefer_host=True
                )
            self.device_batches += 1
            time.sleep(SLOW_DEVICE_S)
            return self._inner.validate_batch(items, run_hooks=run_hooks)

        def validate_batch_finish(self, handle):
            # the split (double-buffered) pipeline blocks on device
            # results here — the simulated slowness must cover it too
            self.device_batches += 1
            time.sleep(SLOW_DEVICE_S)
            return self._inner.validate_batch_finish(handle)

    slow = SlowDeviceEnv(env)
    batcher = MicroBatcher(
        slow,
        max_batch_size=16,
        batch_timeout_ms=0.0,
        policy_timeout=5.0,
        host_fastpath_threshold=0,  # isolate the BUDGET tier from the
        latency_budget_ms=BUDGET_S * 1e3,  # occupancy tier
    ).start()
    try:
        # learning phase: the first dispatches go device-side (the seed
        # estimate comes from warmup, which this test skipped) and teach
        # the router the real RTT
        for _ in range(3):
            batcher.evaluate(
                "priv", pod_review("d", False), RequestOrigin.VALIDATE
            )
        assert slow.device_batches > 0

        # steady state: every batch must now route host-side and finish
        # inside the budget (generous 2x allowance for scheduling jitter)
        lats = []
        for _ in range(20):
            t0 = time.perf_counter()
            r = batcher.evaluate(
                "priv", pod_review("d", True), RequestOrigin.VALIDATE
            )
            lats.append(time.perf_counter() - t0)
            assert not r.allowed  # privileged pod still denied correctly
        assert batcher.budget_routed_batches > 0
        assert max(lats) < 2 * BUDGET_S, (
            f"request waited {max(lats):.3f}s past its "
            f"{BUDGET_S}s budget: {lats}"
        )
    finally:
        batcher.shutdown()


def test_budget_routing_reprobes_after_decay(env):
    """The stored device estimate decays on every budget bypass, so a
    once-slow device is eventually re-probed instead of being pinned
    host-side forever. Drives _dispatch directly for determinism."""
    from concurrent.futures import Future

    from policy_server_tpu.runtime.batcher import _Pending

    batcher = MicroBatcher(
        env,
        max_batch_size=8,
        host_fastpath_threshold=0,
        latency_budget_ms=100.0,
        policy_timeout=None,  # inline dispatch path
    )
    bucket = bucket_size(2)
    batcher._dev_rtt[bucket] = 10.0  # pretend the device measured terrible
    for _ in range(5):
        batch = [
            _Pending(
                "priv", pod_review("d", False), RequestOrigin.VALIDATE,
                Future(),
            ),
            _Pending(
                "priv", pod_review("d", True), RequestOrigin.VALIDATE,
                Future(),
            ),
        ]
        batcher._dispatch(batch)
        assert batch[0].future.result(timeout=5).allowed
        assert not batch[1].future.result(timeout=5).allowed
    assert batcher.budget_routed_batches == 5
    # each bypass decayed the estimate toward an eventual device re-probe
    assert batcher._dev_rtt[bucket] == pytest.approx(10.0 * 0.98**5)
    batcher.shutdown()


def test_rtt_estimator_discards_compile_bearing_samples():
    """Round-14 regression: a dispatch whose window traced a NEW columnar
    plane structure paid a one-time XLA compile — seconds for a mesh
    program — and feeding that one sample into the device-RTT EWMA made
    the budget router send every later batch host-side for the rest of
    the run. _observe_dispatch must discard samples whose window
    advanced the environment's plane_program_compiles counter."""

    class CompilingEnv:
        supports_host_fastpath = True
        plane_program_compiles = 0

    cenv = CompilingEnv()
    batcher = MicroBatcher(
        cenv, max_batch_size=8, latency_budget_ms=100.0, policy_timeout=2.0
    )
    bucket = bucket_size(4)
    batcher._dev_rtt[bucket] = 0.005  # compile-free warmup seed
    # window saw a compile: the 3 s reading is a trace+compile stall,
    # not the steady-state device cost — discarded
    snapshot = cenv.plane_program_compiles
    cenv.plane_program_compiles += 1
    batcher._observe_dispatch(False, bucket, 4, 3.0, compiles_before=snapshot)
    assert batcher._dev_rtt[bucket] == pytest.approx(0.005)
    # compile-free window: the sample feeds the EWMA normally
    batcher._observe_dispatch(
        False, bucket, 4, 0.009,
        compiles_before=cenv.plane_program_compiles,
    )
    assert batcher._dev_rtt[bucket] == pytest.approx(
        0.7 * 0.005 + 0.3 * 0.009
    )
    # a watchdog-abandoned (lower-bound) sample is discarded too when
    # its window compiled — the program exists now; the stall won't recur
    snapshot = cenv.plane_program_compiles
    cenv.plane_program_compiles += 1
    batcher._observe_dispatch(
        False, bucket, 4, 60.0, lower_bound=True, compiles_before=snapshot
    )
    assert batcher._dev_rtt[bucket] < 1.0
    # environments WITHOUT the counter (host oracle, older shims) keep
    # the pre-round-14 behavior: getattr defaults to 0 == compiles_before
    # and every sample feeds in
    class CounterlessEnv:
        supports_host_fastpath = True

    legacy = MicroBatcher(
        CounterlessEnv(), max_batch_size=8, latency_budget_ms=100.0,
        policy_timeout=2.0,
    )
    legacy._dev_rtt[bucket] = 0.005
    legacy._observe_dispatch(False, bucket, 4, 0.02, compiles_before=0)
    assert legacy._dev_rtt[bucket] == pytest.approx(
        0.7 * 0.005 + 0.3 * 0.02
    )


# ---------------------------------------------------------------------------
# fragment fast lane (round 19: pre-serialized cache hits)
# ---------------------------------------------------------------------------


def test_fragment_lane_serves_hits_with_metrics(env):
    """Warm replays through the fused batcher pipeline answer as
    fragment hits: the counter moves, sink verdicts stay correct for
    allowed AND denied shapes, the futures path still yields full
    AdmissionResponses, and every row's evaluation metric is recorded
    (the memoized-metric lane must not drop counts)."""
    import threading as _threading
    import time

    from policy_server_tpu.api import service as service_mod

    batcher = MicroBatcher(
        env,
        max_batch_size=8,
        batch_timeout_ms=1.0,
        policy_timeout=5.0,
        host_fastpath_threshold=0,
        latency_budget_ms=0,
    ).start()

    class Sink:
        def __init__(self):
            self.got = {}
            self.lock = _threading.Lock()

        def deliver_many(self, items):
            with self.lock:
                for token, resp, exc in items:
                    self.got[token] = (resp, exc)

    try:
        items = [
            ("priv", pod_review("default", privileged=False)),
            ("priv", pod_review("default", privileged=True)),
            ("ns", pod_review("blocked", privileged=False)),
        ] * 4
        frag_before = env.dedup_stats["fragment_hits"]
        # wave 1 populates the blob tier (misses), wave 2 hits
        for _wave in range(2):
            sink = Sink()
            batcher.submit_many(
                items, RequestOrigin.VALIDATE, sink=sink,
                tokens=list(range(len(items))),
            )
            deadline = time.perf_counter() + 30
            while time.perf_counter() < deadline:
                with sink.lock:
                    if len(sink.got) == len(items):
                        break
                time.sleep(0.005)
            assert len(sink.got) == len(items)
        assert env.dedup_stats["fragment_hits"] > frag_before
        # hit-wave verdicts: correct allowed/denied split with the
        # denial's status intact
        for i, (pid, _req) in enumerate(items):
            resp, exc = sink.got[i]
            assert exc is None
            if pid == "priv" and i % 3 == 1:
                assert resp.allowed is False
                assert resp.status.code == 400
            elif pid == "ns":
                assert resp.allowed is False
            else:
                assert resp.allowed is True
        # futures path converts fragments back to AdmissionResponse
        fut = batcher.submit(
            "priv", pod_review("default", privileged=True),
            RequestOrigin.VALIDATE,
        )
        resp = fut.result(timeout=30)
        assert type(resp).__name__ == "AdmissionResponse"
        assert resp.allowed is False
        # metrics recorded for every delivered row (memoized lane incl.)
        reg = service_mod._registry()
        total = reg.counter_value(metrics_mod.EVALUATIONS_TOTAL)
        assert total >= 2 * len(items) + 1
    finally:
        batcher.shutdown()
