"""Flight recorder (round 18): ring-wraparound correctness, begin/end
pairing under batch failure paths (shed / pre-encode 504 / device-raise),
the recorder-on-vs-off overhead contract on the batcher serving path,
timeline-export schema validation, exemplar-window semantics, and the
phase-attribution residual math."""

from __future__ import annotations

import json
import time

import pytest

from policy_server_tpu.api.service import RequestOrigin
from policy_server_tpu.evaluation.environment import (
    EvaluationEnvironmentBuilder,
)
from policy_server_tpu.models import AdmissionResponse, ValidateRequest
from policy_server_tpu.models.policy import parse_policy_entry
from policy_server_tpu.runtime.batcher import MicroBatcher, ShedError
from policy_server_tpu.telemetry import flightrec
from policy_server_tpu.telemetry.flightrec import (
    PH_DELIVER,
    PH_DISPATCH,
    PH_FORM,
    PH_QUEUE_WAIT,
    PHASES,
    FlightRecorder,
)

from conftest import build_admission_review_dict


@pytest.fixture(autouse=True)
def no_global_recorder():
    """Every test installs its own recorder; never leak one."""
    yield
    flightrec.install(None)


def _review(name: str = "p") -> ValidateRequest:
    from policy_server_tpu.models import AdmissionReviewRequest

    doc = build_admission_review_dict()
    doc["request"]["object"] = {
        "apiVersion": "v1",
        "kind": "Pod",
        "metadata": {"name": name, "namespace": "default"},
        "spec": {"containers": [{"name": "c", "image": "nginx"}]},
    }
    return ValidateRequest.from_admission(
        AdmissionReviewRequest.from_dict(doc).request
    )


@pytest.fixture(scope="module")
def env():
    policies = {
        "priv": parse_policy_entry(
            "priv", {"module": "builtin://pod-privileged"}
        ),
    }
    e = EvaluationEnvironmentBuilder(backend="jax").build(policies)
    yield e
    e.close()


# ---------------------------------------------------------------------------
# ring mechanics
# ---------------------------------------------------------------------------


def test_ring_wraparound_keeps_last_capacity_events():
    rec = FlightRecorder(capacity=16)
    for i in range(100):
        rec.record_phase(PH_FORM, i * 10, i * 10 + 5, rows=1, batch=i)
    assert rec.events_recorded() == 100
    snap = rec.snapshot()
    assert len(snap) == 16
    # the survivors are exactly the LAST 16 writes, oldest first
    assert [e["seq"] for e in snap] == list(range(84, 100))
    assert [e["batch"] for e in snap] == list(range(84, 100))
    for e in snap:
        assert e["end_ns"] - e["start_ns"] == 5


def test_capacity_rounds_up_to_power_of_two():
    rec = FlightRecorder(capacity=100)
    assert rec._cap == 128


def test_events_are_well_formed_and_ordered():
    rec = FlightRecorder(capacity=64)
    bid = rec.next_batch()
    t = time.perf_counter_ns()
    rec.record_phase(PH_QUEUE_WAIT, t, t + 100, rows=4, batch=bid)
    rec.record_phase(PH_FORM, t + 100, t + 200, rows=4, batch=bid)
    snap = rec.snapshot()
    assert [e["phase"] for e in snap] == [PH_QUEUE_WAIT, PH_FORM]
    assert all(e["kind"] == "batch" for e in snap)
    assert all(e["end_ns"] >= e["start_ns"] for e in snap)


# ---------------------------------------------------------------------------
# serving-path pairing: healthy, shed, expired, device-raise
# ---------------------------------------------------------------------------


class _StubEnvBase:
    """The duck-typed surface the batcher + service halves touch."""

    supports_host_fastpath = False
    always_accept_namespace = None

    def pre_eval_hooks_of(self, target):
        return []

    def _lookup_top_level(self, pid):
        return object()

    def should_always_accept_requests_made_inside_of_namespace(self, ns):
        return False

    def get_policy_mode(self, pid):
        from policy_server_tpu.models.policy import PolicyMode

        return PolicyMode.PROTECT

    def get_policy_allowed_to_mutate(self, pid):
        return False


def _batches_by_id(rec: FlightRecorder) -> dict[int, set[str]]:
    out: dict[int, set[str]] = {}
    for e in rec.snapshot():
        if e["kind"] == "batch" and e["batch"] >= 0:
            out.setdefault(e["batch"], set()).add(e["phase"])
    return out


def test_healthy_batch_records_core_phases(env):
    rec = flightrec.install(FlightRecorder(capacity=4096))
    b = MicroBatcher(
        env, max_batch_size=8, batch_timeout_ms=1.0, policy_timeout=10.0,
        host_fastpath_threshold=0,
    ).start()
    try:
        futs = [
            b.submit("priv", _review(f"p{i}"), RequestOrigin.VALIDATE)
            for i in range(8)
        ]
        for f in futs:
            assert f.result(timeout=15).uid
    finally:
        b.shutdown()
    batches = _batches_by_id(rec)
    assert batches, "no batch events recorded"
    for phases in batches.values():
        # every dispatched batch pairs form+dispatch+deliver around its
        # queue_wait; no dispatch may appear without its form
        assert PH_QUEUE_WAIT in phases and PH_FORM in phases
        if PH_DISPATCH in phases:
            assert PH_DELIVER in phases
    att = rec.attribution()
    assert att["batches_complete"] >= 1
    assert att["rows"] >= 8


def test_shed_burst_records_no_partial_batches(env):
    rec = flightrec.install(FlightRecorder(capacity=1024))
    # dispatch loop NOT started: the queue backs up, and a poisoned RTT
    # estimate makes admission shed everything that follows
    b = MicroBatcher(
        env, max_batch_size=4, batch_timeout_ms=1.0,
        policy_timeout=10.0, request_timeout_ms=50.0, queue_capacity=8,
    )
    try:
        b._dev_rtt[4] = 10.0
        filler = b.submit_nowait(
            "priv", _review("fill"), RequestOrigin.VALIDATE
        )
        with pytest.raises(ShedError):
            b.submit("priv", _review(), RequestOrigin.VALIDATE)
        futs = b.submit_many(
            [("priv", _review(f"s{i}")) for i in range(4)],
            RequestOrigin.VALIDATE,
        )
        for f in futs:
            with pytest.raises(ShedError):
                f.result(timeout=5)
    finally:
        b.shutdown()
    assert filler.result(timeout=5).status.code == 503  # shutdown drain
    # shed rows never formed a batch: the ring holds no batch events at
    # all (nothing dangles half-open)
    assert _batches_by_id(rec) == {}


def test_expired_rows_record_form_without_dispatch(env):
    """Rows whose deadline passes in the queue drop pre-encode (504):
    their batch records queue_wait+form but NO dispatch/deliver — and
    the attribution report simply excludes the incomplete batch."""
    rec = flightrec.install(FlightRecorder(capacity=1024))
    b = MicroBatcher(
        env, max_batch_size=4, batch_timeout_ms=1.0,
        policy_timeout=10.0, request_timeout_ms=30.0,
        host_fastpath_threshold=0,
    )
    # submit BEFORE starting the dispatch loop, then let the deadline
    # lapse: formation happens after expiry
    futs = [
        b.submit_nowait("priv", _review(f"e{i}"), RequestOrigin.VALIDATE)
        for i in range(4)
    ]
    time.sleep(0.08)
    b.start()
    try:
        for f in futs:
            r = f.result(timeout=10)
            assert r.status.code == 504
    finally:
        b.shutdown()
    batches = _batches_by_id(rec)
    assert batches, "expired batch should still record its host phases"
    for phases in batches.values():
        assert PH_FORM in phases
        assert PH_DISPATCH not in phases and PH_DELIVER not in phases
    assert rec.attribution()["batches_complete"] == 0


def test_device_raise_leaves_no_dispatch_event():
    """A validate_batch raise fails the rows in-band; the batch's
    dispatch window never records (excluded from attribution) and no
    later phase dangles."""

    class RaisingEnv(_StubEnvBase):
        def validate_batch(self, items, run_hooks=True, prefer_host=False):
            raise RuntimeError("device fault")

    rec = flightrec.install(FlightRecorder(capacity=256))
    b = MicroBatcher(
        RaisingEnv(), max_batch_size=4, batch_timeout_ms=1.0,
        policy_timeout=5.0, host_fastpath_threshold=0,
    ).start()
    try:
        futs = [
            b.submit_nowait(
                "priv", _review(f"r{i}"), RequestOrigin.VALIDATE
            )
            for i in range(4)
        ]
        for f in futs:
            with pytest.raises(RuntimeError):
                f.result(timeout=10)
    finally:
        b.shutdown()
    for phases in _batches_by_id(rec).values():
        assert PH_DISPATCH not in phases and PH_DELIVER not in phases


# ---------------------------------------------------------------------------
# overhead A/B (the <=2% contract, asserted loosely against CI noise —
# the honest number rides the batcher_serving_path bench line)
# ---------------------------------------------------------------------------


def test_recorder_overhead_on_serving_path():
    class EchoEnv(_StubEnvBase):
        def validate_batch(self, items, run_hooks=True, prefer_host=False):
            return [
                AdmissionResponse(uid=req.uid(), allowed=True)
                for _pid, req in items
            ]

    def drive(n: int) -> float:
        b = MicroBatcher(
            EchoEnv(), max_batch_size=128, batch_timeout_ms=2.0,
            policy_timeout=30.0, host_fastpath_threshold=0,
        ).start()
        try:
            reqs = [_review(f"o{i % 64}") for i in range(256)]
            items = [("priv", reqs[i % 256]) for i in range(n)]
            t0 = time.perf_counter()
            futs = []
            for c in range(0, n, 128):
                futs.extend(
                    b.submit_many(items[c : c + 128], RequestOrigin.VALIDATE)
                )
            for f in futs:
                f.result(timeout=30)
            return time.perf_counter() - t0
        finally:
            b.shutdown()

    n = 6000
    drive(n)  # warm both paths' allocators
    rec = flightrec.install(FlightRecorder(capacity=65536))
    t_on = min(drive(n) for _ in range(2))
    flightrec.install(None)
    events = rec.events_recorded()
    assert events > 0, "recorder saw no events while on"
    # the <=2% contract is judged DETERMINISTICALLY (the wall-clock A/B
    # on a contended CI box flakes on scheduler noise alone — observed;
    # the honest macro A/B lives on the batcher_serving_path bench
    # line): events the recorder actually wrote during the ON drive,
    # costed at the measured per-event price, must stay far under the
    # drive's wall. A recorder accidentally doing per-BATCH work per
    # ROW inflates `events` ~100x and fails this loudly.
    probe = FlightRecorder(capacity=4096)
    t0 = time.perf_counter()
    for i in range(2000):
        probe.record_phase(PH_DISPATCH, i, i + 100, rows=128, batch=i)
    per_event_s = (time.perf_counter() - t0) / 2000
    modeled = events * per_event_s / t_on
    assert modeled < 0.05, (
        f"modeled recorder overhead {modeled:.1%} "
        f"({events} events x {per_event_s * 1e6:.2f}us / {t_on:.2f}s)"
    )


# ---------------------------------------------------------------------------
# timeline export schema
# ---------------------------------------------------------------------------


def test_chrome_trace_schema(env):
    rec = flightrec.install(FlightRecorder(capacity=4096, row_sample_rate=1.0))
    b = MicroBatcher(
        env, max_batch_size=8, batch_timeout_ms=1.0, policy_timeout=10.0,
        host_fastpath_threshold=0,
    ).start()
    try:
        futs = [
            b.submit("priv", _review(f"t{i}"), RequestOrigin.VALIDATE)
            for i in range(8)
        ]
        for f in futs:
            f.result(timeout=15)
    finally:
        b.shutdown()
    doc = json.loads(rec.chrome_trace_json())
    assert doc["displayTimeUnit"] == "ms"
    events = doc["traceEvents"]
    metas = [e for e in events if e["ph"] == "M"]
    slices = [e for e in events if e["ph"] == "X"]
    assert metas and slices
    names = {e["name"] for e in metas}
    assert {"process_name", "thread_name"} <= names
    for e in slices:
        assert e["name"] in PHASES
        assert isinstance(e["ts"], float) and isinstance(e["dur"], float)
        assert e["dur"] >= 0
        assert e["pid"] in (1, 2)
        assert isinstance(e["tid"], int)
        assert "rows" in e["args"] and "batch" in e["args"]
    # row_sample_rate=1.0: sampled-row slices present with uids
    rows = [e for e in slices if e["pid"] == 2]
    assert rows and all(e["args"].get("uid") for e in rows)
    assert doc["otherData"]["events_recorded"] == rec.events_recorded()
    assert isinstance(doc["exemplars"], list) and doc["exemplars"]
    ex = doc["exemplars"][0]
    assert {"trace_id", "policy_id", "latency_seconds",
            "slowest_phase", "phase_breakdown_us"} <= set(ex)


# ---------------------------------------------------------------------------
# exemplar reservoir
# ---------------------------------------------------------------------------


def test_exemplars_keep_slowest_n_with_trace_ids():
    rec = FlightRecorder(capacity=64, exemplar_slots=4)
    t0 = time.perf_counter_ns()
    for i in range(32):
        lat_ns = (i + 1) * 1_000_000
        rec.observe_row(
            f"uid-{i}", "pol", t0, t0 + lat_ns, 1,
            {PH_DISPATCH: lat_ns},
        )
    ex = rec.exemplars()
    assert len(ex) == 4
    assert [e["trace_id"] for e in ex] == [
        "uid-31", "uid-30", "uid-29", "uid-28"
    ]
    assert all(e["slowest_phase"] == PH_DISPATCH for e in ex)
    # the fast path: a row under the floor never takes the lock
    assert rec.row_flags(0.0000001) & FlightRecorder.ROW_EXEMPLAR == 0


def test_exemplar_table_unfreezes_after_spike_window():
    """Post-review regression: a transient spike (boot compiles) fills
    the window and raises the floor; once the window expires, later
    FAST rows must still rotate it (offer-path expiry check) instead of
    serving the stale spike forever — and an idle read rotates too."""
    rec = FlightRecorder(
        capacity=64, exemplar_slots=2, exemplar_window_seconds=0.01
    )
    # done stamps sit at NOW (enqueued in the past): the exemplar
    # window clock keys off completion time
    t0 = time.perf_counter_ns()
    rec.offer_exemplar("spike-a", "pol", t0 - 100_000_000, t0, {})
    rec.offer_exemplar("spike-b", "pol", t0 - 90_000_000, t0, {})
    assert rec._ex_floor > 0
    time.sleep(0.03)
    # a fast row WELL below the spike floor, offered after expiry:
    # the offer must ROTATE (spikes demote to the previous window,
    # floor resets, the fast row enters the new current window) —
    # before the fix the floor gate dropped it and nothing ever rotated
    t1 = time.perf_counter_ns()
    rec.offer_exemplar("fast", "pol", t1 - 1_000_000, t1, {})
    with rec._ex_lock:
        assert [e[1] for e in rec._ex_current] == ["fast"]
        assert rec._ex_floor == 0.0
    # two more idle windows: reads alone age the spike rows out
    time.sleep(0.03)
    rec.exemplars()
    time.sleep(0.03)
    ids = {e["trace_id"] for e in rec.exemplars()}
    assert "spike-a" not in ids and "spike-b" not in ids


def test_exemplars_dedup_duplicate_label_sets():
    """Post-review regression: the uid is client-supplied, and the same
    uid surviving in both the current and previous windows must not
    yield two exemplar entries with identical label tuples — the
    /metrics family would then emit duplicate series and prometheus
    rejects the ENTIRE scrape."""
    rec = FlightRecorder(
        capacity=64, exemplar_slots=4, exemplar_window_seconds=0.01
    )
    t = time.perf_counter_ns()
    rec.offer_exemplar(
        "dup-uid", "pol", t - 50_000_000, t, {PH_DISPATCH: 50_000_000}
    )
    time.sleep(0.03)
    t = time.perf_counter_ns()
    rec.offer_exemplar(
        "dup-uid", "pol", t - 40_000_000, t, {PH_DISPATCH: 40_000_000}
    )
    ex = rec.exemplars()
    assert len(ex) == 1
    # the slowest instance won the dedup
    assert ex[0]["latency_seconds"] == pytest.approx(0.05)


def test_exemplar_window_rotation():
    rec = FlightRecorder(
        capacity=64, exemplar_slots=2, exemplar_window_seconds=0.0
    )
    t0 = time.perf_counter_ns()
    rec.observe_row("old-slow", "pol", t0, t0 + 50_000_000, 1, {})
    # window 0s: the next observation rotates current → previous
    rec.observe_row("new-fast", "pol", t0, t0 + 1_000_000, 1, {})
    ids = {e["trace_id"] for e in rec.exemplars()}
    assert ids == {"old-slow", "new-fast"}  # previous window still visible


# ---------------------------------------------------------------------------
# attribution math
# ---------------------------------------------------------------------------


def test_attribution_residual_math():
    rec = FlightRecorder(capacity=256)
    bid = rec.next_batch()
    # wall 1000ns for 10 rows: form 100, dispatch 800 (600 explained by
    # encode+fetch), deliver 100 → residual = 200 (dispatch gap)
    rec.record_phase(PH_QUEUE_WAIT, 0, 1000, rows=10, batch=bid)
    rec.record_phase(PH_FORM, 1000, 1100, rows=10, batch=bid)
    rec.record_phase(PH_DISPATCH, 1100, 1900, rows=10, batch=bid)
    rec.record_phase(flightrec.PH_ENCODE, 1100, 1500, rows=10, batch=bid)
    rec.record_phase(flightrec.PH_FETCH, 1500, 1700, rows=10, batch=bid)
    rec.record_phase(PH_DELIVER, 1900, 2000, rows=10, batch=bid)
    att = rec.attribution()
    assert att["batches_complete"] == 1
    assert att["rows"] == 10
    assert att["wall_us_per_row"] == pytest.approx(0.1)  # 1000ns/10rows
    assert att["residual_us_per_row"] == pytest.approx(0.02)  # 200ns/10
    assert att["residual_fraction_of_wall"] == pytest.approx(0.2)
    # device_execute never adds to attribution (it nests under fetch)
    rec.record_phase(
        flightrec.PH_DEVICE_EXECUTE, 1500, 1700, rows=10, batch=bid
    )
    assert rec.attribution()["residual_us_per_row"] == pytest.approx(0.02)


def test_attribution_since_cursor_excludes_old_batches():
    rec = FlightRecorder(capacity=256)
    b1 = rec.next_batch()
    rec.record_phase(PH_FORM, 0, 100, rows=1, batch=b1)
    rec.record_phase(PH_DISPATCH, 100, 200, rows=1, batch=b1)
    rec.record_phase(PH_DELIVER, 200, 300, rows=1, batch=b1)
    cursor = rec.events_recorded()
    b2 = rec.next_batch()
    rec.record_phase(PH_FORM, 0, 100, rows=5, batch=b2)
    rec.record_phase(PH_DISPATCH, 100, 200, rows=5, batch=b2)
    rec.record_phase(PH_DELIVER, 200, 300, rows=5, batch=b2)
    att = rec.attribution(since=cursor)
    assert att["batches_complete"] == 1
    assert att["rows"] == 5
