"""Mesh/sharding tests on the 8-virtual-device CPU backend (the v5e-8
stand-in, SURVEY.md §4.2 'Implication for the TPU build'): data-parallel
verdict parity, the acceptance psum collective, policy-sharded MPMD
routing, and mesh planning."""

from __future__ import annotations

import jax
import numpy as np
import pytest

from policy_server_tpu.config.config import MeshSpec
from policy_server_tpu.evaluation.environment import EvaluationEnvironmentBuilder
from policy_server_tpu.models import AdmissionReviewRequest, ValidateRequest
from policy_server_tpu.models.policy import parse_policy_entry
from policy_server_tpu.parallel import (
    DATA_AXIS,
    POLICY_AXIS,
    PolicyShardedEvaluator,
    acceptance_psum,
    make_mesh,
    plan_policy_shards,
)

from conftest import build_admission_review_dict


def pod_request(namespace: str, privileged: bool) -> ValidateRequest:
    doc = build_admission_review_dict()
    doc["request"]["namespace"] = namespace
    doc["request"]["object"] = {
        "apiVersion": "v1",
        "kind": "Pod",
        "metadata": {"name": "p", "namespace": namespace},
        "spec": {
            "containers": [
                {"name": "c", "image": "nginx",
                 "securityContext": {"privileged": privileged}}
            ]
        },
    }
    return ValidateRequest.from_admission(
        AdmissionReviewRequest.from_dict(doc).request
    )


POLICIES = {
    "priv": {"module": "builtin://pod-privileged"},
    "ns": {
        "module": "builtin://namespace-validate",
        "settings": {"denied_namespaces": ["blocked"]},
    },
    "latest": {"module": "builtin://disallow-latest-tag"},
    "happy": {"module": "builtin://always-happy"},
}


def parse_all(policies: dict) -> dict:
    return {k: parse_policy_entry(k, v) for k, v in policies.items()}


def test_make_mesh_shapes():
    mesh = make_mesh(MeshSpec.parse("data:8"))
    assert mesh.shape[DATA_AXIS] == 8 and mesh.shape[POLICY_AXIS] == 1
    mesh = make_mesh(MeshSpec.parse("data:4,policy:2"))
    assert mesh.shape[DATA_AXIS] == 4 and mesh.shape[POLICY_AXIS] == 2
    mesh = make_mesh(MeshSpec.parse("auto"))
    assert mesh.shape[DATA_AXIS] == len(jax.devices())
    with pytest.raises(ValueError):
        make_mesh(MeshSpec.parse("data:3,policy:2"))


def test_data_parallel_matches_single_device():
    single = EvaluationEnvironmentBuilder(backend="jax").build(parse_all(POLICIES))
    sharded = EvaluationEnvironmentBuilder(backend="jax").build(parse_all(POLICIES))
    sharded.attach_mesh(make_mesh(MeshSpec.parse("data:8")))
    cases = [
        ("priv", pod_request("default", True)),
        ("priv", pod_request("default", False)),
        ("ns", pod_request("blocked", False)),
        ("ns", pod_request("fine", False)),
        ("latest", pod_request("default", False)),
        ("happy", pod_request("default", True)),
    ]
    a = single.validate_batch(cases)
    b = sharded.validate_batch(cases)
    assert [r.to_dict() for r in a] == [r.to_dict() for r in b]
    # single-request path also pads to the data-axis bucket
    r1 = single.validate("priv", pod_request("x", True))
    r2 = sharded.validate("priv", pod_request("x", True))
    assert r1.to_dict() == r2.to_dict()


def test_acceptance_psum_collective():
    mesh = make_mesh(MeshSpec.parse("data:8"))
    count = acceptance_psum(mesh)
    allowed = np.zeros((16, 3), dtype=bool)
    allowed[:5, 0] = True
    allowed[:, 1] = True
    counts = np.asarray(count(allowed))
    assert counts.tolist() == [5, 16, 0]


def test_plan_policy_shards_partition():
    mesh = make_mesh(MeshSpec.parse("data:4,policy:2"))
    plans = plan_policy_shards(list(POLICIES), mesh)
    assert len(plans) == 2
    all_ids = sorted(pid for p in plans for pid in p.policy_ids)
    assert all_ids == sorted(POLICIES)
    for p in plans:
        assert p.mesh.shape[DATA_AXIS] == 4


def test_policy_sharded_evaluator_matches_single():
    single = EvaluationEnvironmentBuilder(backend="jax").build(parse_all(POLICIES))
    mesh = make_mesh(MeshSpec.parse("data:4,policy:2"))
    sharded = PolicyShardedEvaluator(parse_all(POLICIES), mesh)
    cases = [
        ("priv", pod_request("default", True)),
        ("ns", pod_request("blocked", False)),
        ("latest", pod_request("default", False)),
        ("happy", pod_request("default", False)),
        ("priv", pod_request("default", False)),
    ]
    a = single.validate_batch(cases)
    b = sharded.validate_batch(cases)
    assert [r.to_dict() for r in a] == [r.to_dict() for r in b]

    from policy_server_tpu.evaluation.errors import PolicyNotFoundError

    out = sharded.validate_batch([("missing", pod_request("d", False))])
    assert isinstance(out[0], PolicyNotFoundError)


def test_policy_sharded_preemption_churn_resize():
    """BASELINE config 5 preemption churn: dropping devices between
    batches rebuilds/rebalances the shard set over the survivors and
    serving continues with identical verdicts."""
    mesh = make_mesh(MeshSpec.parse("data:4,policy:2"))
    sharded = PolicyShardedEvaluator(parse_all(POLICIES), mesh)
    cases = [
        ("priv", pod_request("default", True)),
        ("ns", pod_request("blocked", False)),
        ("latest", pod_request("default", False)),
        ("happy", pod_request("default", False)),
    ]
    before = [r.to_dict() for r in sharded.validate_batch(cases)]
    assert len(sharded.shards) == 2

    # two chips preempted: 8 → 6 devices; policy axis re-factors (2 | 6)
    survivors = list(jax.devices())[:6]
    sharded.resize(survivors)
    assert sharded.resizes == 1
    assert sharded.mesh.devices.size == 6
    assert sharded.mesh.shape[POLICY_AXIS] == 2
    assert sharded.mesh.shape[DATA_AXIS] == 3
    after = [r.to_dict() for r in sharded.validate_batch(cases)]
    assert after == before
    assert sorted(sharded.policy_ids()) == sorted(POLICIES)

    # a second shrink to a device count the policy axis does not divide:
    # 6 → 5 devices forces a single-shard layout
    sharded.resize(list(jax.devices())[:5])
    assert sharded.mesh.shape[POLICY_AXIS] == 1
    assert [r.to_dict() for r in sharded.validate_batch(cases)] == before

    with pytest.raises(ValueError, match="empty device set"):
        sharded.resize([])


def test_policy_sharded_resize_during_inflight_batch():
    """A resize concurrent with serving: in-flight batches finish on the
    old shards; new batches route through the new set — no torn routing."""
    import threading

    mesh = make_mesh(MeshSpec.parse("data:4,policy:2"))
    sharded = PolicyShardedEvaluator(parse_all(POLICIES), mesh)
    cases = [("priv", pod_request("default", True)),
             ("ns", pod_request("blocked", False))] * 8
    expected = [r.to_dict() for r in sharded.validate_batch(cases)]

    stop = threading.Event()
    failures: list = []

    def serve() -> None:
        while not stop.is_set():
            try:
                got = [r.to_dict() for r in sharded.validate_batch(cases)]
                if got != expected:
                    failures.append("verdict drift")
                    return
            except Exception as e:  # noqa: BLE001
                failures.append(repr(e))
                return

    t = threading.Thread(target=serve, daemon=True)
    t.start()
    try:
        sharded.resize(list(jax.devices())[:4])
        sharded.resize(list(jax.devices()))
    finally:
        stop.set()
        t.join(timeout=30)
    assert not failures, failures


def test_policy_sharded_retired_snapshot_closes_on_drain():
    """Drain-based retirement (ADVICE r4): a resize must NOT close the old
    shard environments while a dispatch is still pinned to them — however
    long it takes (the old 30s wall-clock grace shut pools down under a
    post-churn lazy-compile stall) — and must close them exactly when the
    last pinned dispatch drains."""
    import threading

    mesh = make_mesh(MeshSpec.parse("data:4,policy:2"))
    sharded = PolicyShardedEvaluator(parse_all(POLICIES), mesh)
    old_envs = list(sharded.shards)
    closed = {id(env): False for env in old_envs}
    originals = {}
    for env in old_envs:
        originals[id(env)] = env.close

        def make_close(e):
            orig = originals[id(e)]

            def _close():
                closed[id(e)] = True
                orig()

            return _close

        env.close = make_close(env)

    entered = threading.Event()
    release = threading.Event()
    target_env = sharded._shard_of("priv")  # the shard the dispatch hits
    orig_vb = target_env.validate_batch

    def blocking_vb(items, **kw):
        entered.set()
        assert release.wait(timeout=30), "test deadlock"
        return orig_vb(items, **kw)

    target_env.validate_batch = blocking_vb

    cases = [("priv", pod_request("default", True))]
    result: list = []
    t = threading.Thread(
        target=lambda: result.append(sharded.validate_batch(cases)),
        daemon=True,
    )
    t.start()
    assert entered.wait(timeout=30)

    # resize while the dispatch is pinned: retired envs must stay open
    sharded.resize(list(jax.devices())[:4])
    assert not any(closed.values()), "retired env closed mid-flight"

    release.set()
    t.join(timeout=30)
    assert not t.is_alive()
    assert result and not isinstance(result[0][0], Exception)
    # the drain of the last pinned dispatch closed every retired env
    assert all(closed.values()), "retired envs never closed after drain"
    # the current routing is untouched
    verdicts = sharded.validate_batch(cases)
    assert not isinstance(verdicts[0], Exception)
    sharded.close()


def test_policy_sharded_group_routing():
    policies = dict(POLICIES)
    policies["grp"] = {
        "expression": "a() && b()",
        "message": "denied",
        "policies": {
            "a": {"module": "builtin://always-happy"},
            "b": {"module": "builtin://pod-privileged"},
        },
    }
    mesh = make_mesh(MeshSpec.parse("data:4,policy:2"))
    sharded = PolicyShardedEvaluator(parse_all(policies), mesh)
    resp = sharded.validate("grp", pod_request("default", True))
    assert not resp.allowed
    assert resp.status.details.causes[0].field == "spec.policies.b"


def test_unreferenced_group_member_mask(request):
    """A member defined but not referenced by the expression is never
    evaluated (regression: packed outputs raised KeyError at trace time)."""
    policies = {
        "g": parse_policy_entry(
            "g",
            {
                "expression": "happy()",
                "message": "denied",
                "policies": {
                    "happy": {"module": "builtin://always-happy"},
                    "extra": {"module": "builtin://pod-privileged"},
                },
            },
        )
    }
    env = EvaluationEnvironmentBuilder(backend="jax").build(policies)
    resp = env.validate("g", pod_request("default", True))
    assert resp.allowed


def test_bucket_for_non_pow2_data_axis():
    env = EvaluationEnvironmentBuilder(backend="jax").build(
        parse_all({"happy": {"module": "builtin://always-happy"}})
    )
    env._min_bucket = 6  # simulate a 6-wide data axis
    assert env.bucket_for(5) % 6 == 0
    assert env.bucket_for(13) % 6 == 0


def test_sharded_evaluator_hooks_through_batcher():
    """Regression: pre_eval_hooks_of raised NotImplementedError and killed
    every batched request on a sharded evaluator."""
    from policy_server_tpu.api.service import RequestOrigin
    from policy_server_tpu.runtime.batcher import MicroBatcher

    mesh = make_mesh(MeshSpec.parse("data:4,policy:2"))
    sharded = PolicyShardedEvaluator(parse_all(POLICIES), mesh)
    batcher = MicroBatcher(sharded, host_fastpath_threshold=0, max_batch_size=4, batch_timeout_ms=1.0).start()
    try:
        resp = batcher.evaluate(
            "priv", pod_request("default", True), RequestOrigin.VALIDATE,
            timeout=30,
        )
        assert not resp.allowed
    finally:
        batcher.shutdown()
