"""TLS rotation + handshake-abuse chaos suite (``make chaos``).

Round 20's native TLS termination under the storms round 13 built for
the plaintext surface: sustained HTTPS traffic across a SIGHUP-driven
certificate rotation (zero unexplained non-2xx; established connections
finish on the identity they pinned at accept), a corrupted-cert reload
that must keep last-good serving, and the ``tls.handshake`` failpoint
arming/disarming the native accept path. Runs under the lock-order
sanitizer via ``make chaos`` — the SSL_CTX generation swap takes
certs.py's lock, the manager's lock, and the frontend's lock on
different threads, and 0 inversions is part of the acceptance bar."""

from __future__ import annotations

import json
import shutil
import socket
import ssl
import threading
import time

import pytest
import requests

from test_server import ServerHandle, make_config, pod_review_body
from policy_server_tpu import failpoints
from policy_server_tpu.config import TlsConfig
from policy_server_tpu.telemetry import metrics as metrics_mod
from tools import tlsgen

nf = pytest.importorskip(
    "policy_server_tpu.runtime.native_frontend",
    reason="native frontend module unavailable",
)

pytestmark = [
    pytest.mark.skipif(
        not nf.native_available(),
        reason="httpfront.cpp failed to build (no g++?)",
    ),
    pytest.mark.skipif(
        not tlsgen.openssl_available(),
        reason="openssl CLI unavailable — cannot mint test certificates",
    ),
    pytest.mark.skipif(
        nf.native_available() and not nf.tls_available(),
        reason="libssl unavailable — the rotation storm needs native "
        "TLS termination",
    ),
]


@pytest.fixture(autouse=True)
def clean_failpoints():
    failpoints.reset()
    yield
    failpoints.reset()


@pytest.fixture(autouse=True)
def fresh_metrics():
    metrics_mod.reset_metrics_for_tests()
    yield


@pytest.fixture()
def tls_server(tmp_path):
    """A native-TLS server over a MUTABLE identity directory (rotation
    tests overwrite the files in place, like a real cert-manager
    volume)."""
    cert, key = tlsgen.self_signed_identity(tmp_path, cn="original")
    tls = TlsConfig(cert_file=str(cert), key_file=str(key))
    handle = ServerHandle(make_config(frontend="native", tls_config=tls))
    assert handle.server._native_tls is not None, (
        "TLS did not terminate natively despite tls_available()"
    )
    yield handle, tmp_path
    handle.stop()


def client_ctx() -> ssl.SSLContext:
    ctx = ssl.create_default_context()
    ctx.check_hostname = False
    ctx.verify_mode = ssl.CERT_NONE
    return ctx


def _peer_cn(sock: ssl.SSLSocket) -> str:
    """CN of the peer certificate via the openssl CLI (the container
    has no ``cryptography`` package)."""
    import subprocess

    der = sock.getpeercert(binary_form=True)
    proc = subprocess.run(
        ["openssl", "x509", "-inform", "der", "-noout", "-subject"],
        input=der, capture_output=True,
    )
    return proc.stdout.decode().strip()


def test_tls_rotation_under_load_storm(tls_server):
    """SIGHUP mid-storm rotates the serving identity: zero unexplained
    non-2xx through the swap, NEW connections handshake under the new
    certificate, and a connection ESTABLISHED before the rotation keeps
    serving on the old one (it drains, never renegotiates)."""
    handle, certdir = tls_server
    server = handle.server
    port = server.api_port
    stop = threading.Event()
    results: list[int] = []
    errors: list[Exception] = []

    def traffic() -> None:
        s = requests.Session()
        while not stop.is_set():
            try:
                r = s.post(
                    f"https://127.0.0.1:{port}/validate/pod-privileged",
                    json=pod_review_body(False), verify=False, timeout=30,
                )
                results.append(r.status_code)
            except Exception as e:  # noqa: BLE001 — recorded for assert
                errors.append(e)
                return

    threads = [
        threading.Thread(target=traffic, daemon=True) for _ in range(3)
    ]
    established = client_ctx().wrap_socket(
        socket.create_connection(("127.0.0.1", port))
    )
    assert "original" in _peer_cn(established)
    body = json.dumps(pod_review_body(False)).encode()
    req = (
        b"POST /validate/pod-privileged HTTP/1.1\r\nHost: t\r\n"
        b"Content-Type: application/json\r\n"
        b"Content-Length: %d\r\n\r\n%s" % (len(body), body)
    )
    established.sendall(req)
    assert established.recv(65536).startswith(b"HTTP/1.1 200")
    try:
        for t in threads:
            t.start()
        time.sleep(0.3)
        before = server._native_tls.snapshot()["generations"]
        # rotate in place, then the SIGHUP contract entry point
        # (ServerHandle's loop thread cannot take real signals)
        cert2, key2 = tlsgen.self_signed_identity(
            certdir, cn="rotated", stem="next"
        )
        shutil.copy(cert2, certdir / "server.pem")
        shutil.copy(key2, certdir / "server-key.pem")
        server.reload_signal()
        deadline = time.monotonic() + 30
        while (
            server._native_tls.snapshot()["generations"] == before
            and time.monotonic() < deadline
        ):
            time.sleep(0.05)
        snap = server._native_tls.snapshot()
        assert snap["generations"] > before, "rotation never installed"
        assert snap["failed_swaps"] == 0
        time.sleep(0.3)  # traffic THROUGH the new generation
        # new connections pin the rotated identity...
        fresh = client_ctx().wrap_socket(
            socket.create_connection(("127.0.0.1", port))
        )
        assert "rotated" in _peer_cn(fresh)
        fresh.close()
        # ...while the pre-rotation connection keeps serving on the old
        established.sendall(req)
        assert established.recv(65536).startswith(b"HTTP/1.1 200")
        assert "original" in _peer_cn(established)
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=10)
        established.close()
    assert not errors, errors
    assert len(results) > 20
    non_2xx = [c for c in results if c != 200]
    assert not non_2xx, f"non-2xx during TLS rotation: {non_2xx[:5]}"


def test_tls_corrupted_reload_keeps_last_good(tls_server):
    """Garbage cert material mid-rotation: the reload fails LOUDLY, the
    failure is counted, no new SSL_CTX generation installs, and the
    last-good identity keeps serving new handshakes."""
    handle, certdir = tls_server
    server = handle.server
    port = server.api_port
    before = server._native_tls.snapshot()
    (certdir / "server.pem").write_text("-----NOT A CERT-----\n")
    server.reload_signal()
    rel = server.tls_context._reloadable
    deadline = time.monotonic() + 15
    while (
        rel.counters()[1] == before["reload_failures"]
        and time.monotonic() < deadline
    ):
        time.sleep(0.05)
    after = server._native_tls.snapshot()
    assert after["reload_failures"] > before["reload_failures"]
    assert after["generations"] == before["generations"]
    assert after["failed_swaps"] == 0  # the rebuild was never attempted
    s = client_ctx().wrap_socket(
        socket.create_connection(("127.0.0.1", port))
    )
    assert "original" in _peer_cn(s), "last-good identity was lost"
    r = requests.post(
        f"https://127.0.0.1:{port}/validate/pod-privileged",
        json=pod_review_body(True), verify=False, timeout=30,
    )
    assert r.status_code == 200
    assert r.json()["response"]["allowed"] is False
    s.close()


def test_tls_handshake_failpoint_arms_and_recovers(tls_server):
    """An armed raising ``tls.handshake`` site makes the native loops
    refuse EVERY new handshake (counted, alert sent); disarming restores
    service — and established connections never notice."""
    handle, _certdir = tls_server
    server = handle.server
    port = server.api_port
    manager = server._native_tls
    established = client_ctx().wrap_socket(
        socket.create_connection(("127.0.0.1", port))
    )

    def boom() -> None:
        raise failpoints.FailpointError("injected TLS accept outage")

    failpoints.set_failpoint("tls.handshake", boom)
    manager.poll_failpoint_once()  # deterministic arm, no poll-loop wait
    assert failpoints.fired_count("tls.handshake") > 0
    with pytest.raises((ssl.SSLError, OSError)):
        s = client_ctx().wrap_socket(
            socket.create_connection(("127.0.0.1", port))
        )
        s.settimeout(5)
        if s.recv(1) == b"":  # a bare close is a refusal too
            raise ssl.SSLError("refused")
    front = server._native_frontend
    deadline = time.monotonic() + 5
    while (
        front.stats()["tls_handshakes_fail_injected"] == 0
        and time.monotonic() < deadline
    ):
        time.sleep(0.05)
    assert front.stats()["tls_handshakes_fail_injected"] >= 1

    failpoints.reset()
    manager.poll_failpoint_once()  # deterministic disarm
    ok = client_ctx().wrap_socket(
        socket.create_connection(("127.0.0.1", port))
    )
    assert ok.version() is not None, "service did not recover"
    ok.close()
    # the established connection rode through armed + disarmed windows
    body = json.dumps(pod_review_body(False)).encode()
    established.sendall(
        b"POST /validate/pod-privileged HTTP/1.1\r\nHost: t\r\n"
        b"Content-Type: application/json\r\n"
        b"Content-Length: %d\r\nConnection: close\r\n\r\n%s"
        % (len(body), body)
    )
    assert established.recv(65536).startswith(b"HTTP/1.1 200")
    established.close()
