"""Native HTTP front-end tests (csrc/httpfront.cpp +
runtime/native_frontend.py).

The core is the DIFFERENTIAL FRAMING CORPUS: the same raw byte streams —
valid, malformed, oversized, chunked, keep-alive, pipelined, unicode,
float-bearing, duplicate-keyed, mid-body-disconnected — replayed against
two live servers that differ ONLY in ``--frontend``; status lines, headers
(incl. Retry-After; the Date value is the one excluded volatile), and body
bytes must match exactly. The Python (aiohttp) frontend is the correctness
oracle; the native frontend earns its throughput by being
indistinguishable from it.

Also covered: graceful degradation when the extension cannot build/load
(loud warning, automatic Python fallback, server still boots and serves —
the round-7 soft-dep pattern)."""

from __future__ import annotations

import json
import socket
import time

import pytest
import requests

from test_server import ServerHandle, make_config, pod_review_body

nf = pytest.importorskip(
    "policy_server_tpu.runtime.native_frontend",
    reason="native frontend module unavailable",
)

pytestmark = pytest.mark.skipif(
    not nf.native_available(),
    reason="httpfront.cpp failed to build (no g++?) — the server "
    "degrades to the Python frontend, covered by test_fallback below",
)


@pytest.fixture(scope="module")
def pair():
    """One policy set, two frontends: (python_handle, native_handle)."""
    from policy_server_tpu.telemetry import metrics as metrics_mod

    metrics_mod.reset_metrics_for_tests()
    py = ServerHandle(make_config(frontend="python"))
    nat = ServerHandle(make_config(frontend="native"))
    assert nat.server._native_frontend is not None, (
        "native frontend did not come up despite native_available()"
    )
    yield py, nat
    nat.stop()
    py.stop()


# -- raw-socket helpers ------------------------------------------------------


def send_raw(port: int, data: bytes, timeout: float = 15.0) -> bytes:
    s = socket.create_connection(("127.0.0.1", port))
    try:
        s.sendall(data)
        s.settimeout(timeout)
        out = b""
        try:
            while True:
                chunk = s.recv(65536)
                if not chunk:
                    break
                out += chunk
        except socket.timeout:
            pass
        return out
    finally:
        s.close()


def parse_responses(stream: bytes) -> list[tuple[str, dict, bytes]]:
    """Split a byte stream into (status_line, headers, body) responses.
    100-continue interim responses are kept as body-less entries."""
    out = []
    rest = stream
    while rest:
        head_end = rest.find(b"\r\n\r\n")
        if head_end < 0:
            out.append(("<trailing-garbage>", {}, rest))
            break
        head = rest[:head_end].decode("latin-1")
        rest = rest[head_end + 4 :]
        lines = head.split("\r\n")
        status_line = lines[0]
        headers: dict[str, str] = {}
        for ln in lines[1:]:
            k, _, v = ln.partition(":")
            headers[k.strip().lower()] = v.strip()
        if status_line.endswith("100 Continue"):
            out.append((status_line, headers, b""))
            continue
        n = int(headers.get("content-length", "0"))
        out.append((status_line, headers, rest[:n]))
        rest = rest[n:]
    return out


def normalize(parsed, drop=("date",)):
    return [
        (status, {k: v for k, v in hdrs.items() if k not in drop}, body)
        for status, hdrs, body in parsed
    ]


def assert_identical(pair, payload: bytes, n_responses: int | None = None):
    py, nat = pair
    a = normalize(parse_responses(send_raw(py.server.api_port, payload)))
    b = normalize(parse_responses(send_raw(nat.server.api_port, payload)))
    assert a == b, (
        f"frontends diverged for {payload[:120]!r}...\n"
        f"python: {a}\nnative: {b}"
    )
    if n_responses is not None:
        assert len(a) == n_responses
    return a


def post_bytes(
    path: str, body: bytes, close: bool = True, extra: str = ""
) -> bytes:
    head = (
        f"POST {path} HTTP/1.1\r\nHost: t\r\n"
        f"Content-Type: application/json\r\n"
        f"Content-Length: {len(body)}\r\n{extra}"
    )
    if close:
        head += "Connection: close\r\n"
    return head.encode() + b"\r\n" + body


def review(obj=None, **request_overrides) -> bytes:
    doc = pod_review_body(False)
    if obj is not None:
        doc["request"]["object"] = obj
    doc["request"].update(request_overrides)
    return json.dumps(doc).encode()


# -- the differential corpus -------------------------------------------------


def test_valid_verdicts_bit_exact(pair):
    for privileged in (True, False):
        body = json.dumps(pod_review_body(privileged)).encode()
        (status, _h, resp) = assert_identical(
            pair, post_bytes("/validate/pod-privileged", body), 1
        )[0]
        assert status == "HTTP/1.1 200 OK"
        assert json.loads(resp)["response"]["allowed"] is (not privileged)


def test_keep_alive_and_pipelining(pair):
    one = post_bytes(
        "/validate/pod-privileged",
        json.dumps(pod_review_body(False)).encode(),
        close=False,
    )
    two = post_bytes(
        "/validate/pod-privileged",
        json.dumps(pod_review_body(True)).encode(),
    )
    resps = assert_identical(pair, one + two, 2)
    assert all(s == "HTTP/1.1 200 OK" for s, _h, _b in resps)
    # keep-alive first response carries no Connection header; the closer does
    assert "connection" not in resps[0][1]
    assert resps[1][1].get("connection") == "close"


def test_malformed_and_undeserializable_bodies(pair):
    cases = [
        b"not json at all",
        b"{",
        b'{"request": "not an object"}',
        b'{"nope": 1}',                      # missing request
        b'{"request": {"operation": "CREATE"}}',  # missing uid
        b'{"request": {"uid": ""}}',        # empty uid
        b'{"request": {"uid": 42}}',        # non-string uid
        b'{"request": {"uid": "u", "kind": "Pod"}}',  # non-object kind
        json.dumps({"request": {"uid": "u"}, "extra": [1, {"a": None}]}).encode(),
    ]
    for body in cases:
        (status, _h, resp) = assert_identical(
            pair, post_bytes("/validate/pod-privileged", body), 1
        )[0]
        if body == cases[-1]:
            assert status == "HTTP/1.1 200 OK"
        else:
            assert status == "HTTP/1.1 422 Unprocessable Entity", resp


def test_routing_404_405(pair):
    a = assert_identical(
        pair, post_bytes("/no/such/route", b"{}"), 1
    )
    assert a[0][0] == "HTTP/1.1 404 Not Found"
    a = assert_identical(
        pair,
        b"GET /validate/pod-privileged HTTP/1.1\r\nHost: t\r\n"
        b"Connection: close\r\n\r\n",
        1,
    )
    assert a[0][0] == "HTTP/1.1 405 Method Not Allowed"
    assert a[0][1]["allow"] == "POST"
    a = assert_identical(
        pair,
        post_bytes("/validate/nope", json.dumps(pod_review_body(False)).encode()),
        1,
    )
    assert a[0][0] == "HTTP/1.1 404 Not Found"  # PolicyNotFound, JSON body
    assert json.loads(a[0][2])["status"] == 404


def test_oversized_bodies(pair):
    """413 parity, modulo the trailing byte count: aiohttp reports the
    bytes it had read when the cap tripped — a transport-chunking
    artifact that varies run to run — while the native frontend reports
    the full (deterministic) body size. Status line, headers, and the
    message prefix must match; the native number must be exact."""
    import re

    def mask(resps):
        return [
            (s, h, re.sub(rb"actual body size \d+", b"actual body size N", b))
            for s, h, b in resps
        ]

    py, nat = pair
    cases = []
    big = review(obj={"filler": "x" * (9 * 1024 * 1024)})
    cases.append((post_bytes("/validate/pod-privileged", big), len(big)))
    payload = b"y" * (9 * 1024 * 1024)
    chunked = (
        b"POST /validate/pod-privileged HTTP/1.1\r\nHost: t\r\n"
        b"Transfer-Encoding: chunked\r\nConnection: close\r\n\r\n"
        + hex(len(payload))[2:].encode() + b"\r\n" + payload + b"\r\n0\r\n\r\n"
    )
    cases.append((chunked, len(payload)))
    for wire, total in cases:
        a = normalize(parse_responses(send_raw(py.server.api_port, wire)))
        b = normalize(parse_responses(send_raw(nat.server.api_port, wire)))
        # content-length differs only through the masked digits
        for resps in (a, b):
            for _s, h, _b in resps:
                h.pop("content-length", None)
        assert mask(a) == mask(b), f"python: {a}\nnative: {b}"
        assert a[0][0] == "HTTP/1.1 413 Request Entity Too Large"
        assert b[0][2] == (
            f"Maximum request body size 8388608 exceeded, actual body "
            f"size {total}"
        ).encode()


def test_chunked_valid_body(pair):
    body = json.dumps(pod_review_body(True)).encode()
    mid = len(body) // 2
    chunked = (
        b"POST /validate/pod-privileged HTTP/1.1\r\nHost: t\r\n"
        b"Transfer-Encoding: chunked\r\nConnection: close\r\n\r\n"
        + hex(mid)[2:].encode() + b"\r\n" + body[:mid] + b"\r\n"
        + hex(len(body) - mid)[2:].encode() + b"\r\n" + body[mid:]
        + b"\r\n0\r\n\r\n"
    )
    a = assert_identical(pair, chunked, 1)
    assert a[0][0] == "HTTP/1.1 200 OK"
    assert json.loads(a[0][2])["response"]["allowed"] is False


def test_expect_100_continue(pair):
    body = json.dumps(pod_review_body(False)).encode()
    a = assert_identical(
        pair,
        post_bytes(
            "/validate/pod-privileged", body,
            extra="Expect: 100-continue\r\n",
        ),
        2,
    )
    assert a[0][0].endswith("100 Continue")
    assert a[1][0] == "HTTP/1.1 200 OK"


def test_canonicalization_parity_unicode_and_shapes(pair):
    """Payload shapes that stress the native canonicalizer: non-ASCII
    (ensure_ascii escaping), astral plane, null-dropping, requestKind
    normalization, unknown request keys, empty userInfo."""
    doc = {
        "apiVersion": "admission.k8s.io/v1",
        "kind": "AdmissionReview",
        "request": {
            "unknownKey": {"deep": [1, 2, {"x": "y"}]},
            "uid": "uid-üñí-😀",
            "operation": "CREATE",
            "name": None,
            "namespace": "späce",
            "requestKind": {"version": "v1", "kind": "Pod", "junk": 1},
            "userInfo": {},
            "dryRun": False,
            "object": {
                "metadata": {
                    "labels": {"app": "ünïcode- -😀", "tab": "a\tb"},
                    "annotations": {"empty": "", "ctl": "\x01\x7f"},
                },
                "spec": {
                    "containers": [
                        {"name": "c", "securityContext": {"privileged": True}}
                    ]
                },
            },
        },
    }
    a = assert_identical(
        pair,
        post_bytes("/validate/pod-privileged", json.dumps(doc).encode()),
        1,
    )
    assert a[0][0] == "HTTP/1.1 200 OK"
    assert json.loads(a[0][2])["response"]["allowed"] is False
    assert json.loads(a[0][2])["response"]["uid"] == "uid-üñí-😀"


def test_python_fallback_shapes_still_bit_exact(pair):
    """Constructs the native parser deliberately declines (floats,
    duplicate keys, deep nesting, NaN) must round-trip through the
    Python parse oracle with identical answers."""
    float_doc = review(obj={"spec": {"weight": 0.25, "big": 1e30}})
    dup = (
        b'{"request": {"uid": "u1", "object": {"a": 1, "a": 2}, '
        b'"operation": "CREATE"}}'
    )
    deep_obj: dict = {"leaf": 1}
    for _ in range(120):
        deep_obj = {"n": deep_obj}
    deep = review(obj=deep_obj)
    nan = b'{"request": {"uid": "u2", "object": {"v": NaN}}}'
    for body in (float_doc, dup, deep, nan):
        a = assert_identical(
            pair, post_bytes("/validate/pod-privileged", body), 1
        )
        assert a[0][0] == "HTTP/1.1 200 OK", a[0][2]


def test_canonical_expansion_overflow_falls_back(pair):
    """ensure_ascii escaping can expand multibyte UTF-8 ~3x: a body that
    fits the 8 MiB cap but whose CANONICAL form would not must ship the
    raw body to the Python oracle (bounded record) instead of producing
    an oversized record that could wedge the submission ring."""
    emoji_mb = "😀" * (1024 * 1024)  # 4 MiB of raw UTF-8 → ~12 MiB escaped
    doc = json.loads(review())
    doc["request"]["object"] = {"notes": emoji_mb}
    # ensure_ascii=False: the WIRE carries compact UTF-8; only the
    # canonicalizer's ensure_ascii output would blow past the cap
    body = json.dumps(doc, ensure_ascii=False).encode()
    assert len(body) < 8 * 1024**2
    _py, nat = pair
    fallbacks_before = nat.server._native_frontend.stats()["parse_fallbacks"]
    a = assert_identical(
        pair, post_bytes("/validate/pod-privileged", body), 1
    )
    assert a[0][0] == "HTTP/1.1 200 OK"
    assert (
        nat.server._native_frontend.stats()["parse_fallbacks"]
        > fallbacks_before
    )


def test_validate_raw_and_audit_parity(pair):
    raw_bad = b"steak"
    a = assert_identical(
        pair, post_bytes("/validate_raw/raw-mutation", raw_bad), 1
    )
    assert a[0][0] == "HTTP/1.1 422 Unprocessable Entity"

    raw_ok = json.dumps({"request": {"uid": "raw-1", "user": "x"}}).encode()
    a = assert_identical(
        pair, post_bytes("/validate_raw/raw-mutation", raw_ok), 1
    )
    assert a[0][0] == "HTTP/1.1 200 OK"
    assert "response" in json.loads(a[0][2])

    audit_body = json.dumps(pod_review_body(True)).encode()
    a = assert_identical(
        pair, post_bytes("/audit/pod-privileged", audit_body), 1
    )
    assert a[0][0] == "HTTP/1.1 200 OK"
    assert json.loads(a[0][2])["response"]["allowed"] is False


def test_mid_body_disconnect_leaves_server_serving(pair):
    """A client dying mid-body gets no response from either frontend, and
    neither server may be degraded by it."""
    py, nat = pair
    for handle in (py, nat):
        s = socket.create_connection(("127.0.0.1", handle.server.api_port))
        s.sendall(
            b"POST /validate/pod-privileged HTTP/1.1\r\nHost: t\r\n"
            b"Content-Length: 5000\r\n\r\npartial-body-then-gone"
        )
        s.close()
    time.sleep(0.2)
    body = json.dumps(pod_review_body(False)).encode()
    a = assert_identical(
        pair, post_bytes("/validate/pod-privileged", body), 1
    )
    assert a[0][0] == "HTTP/1.1 200 OK"


def test_malformed_request_line_status_parity(pair):
    """Framing garbage: both answer 400 (bodies differ — aiohttp embeds
    the offending bytes — so this case compares status codes only)."""
    py, nat = pair
    for handle in (py, nat):
        out = send_raw(handle.server.api_port, b"BLARGH\r\n\r\n")
        assert b" 400 " in out.split(b"\r\n", 1)[0], out[:100]


def test_smuggling_vectors_rejected_with_400(pair):
    """Duplicate Content-Length and Content-Length+chunked are request-
    smuggling vectors: both frontends must refuse to frame them (status
    parity; aiohttp's llhttp rejects with 400)."""
    py, nat = pair
    vectors = [
        b"POST /validate/pod-privileged HTTP/1.1\r\nHost: t\r\n"
        b"Content-Length: 2\r\nContent-Length: 5\r\n"
        b"Connection: close\r\n\r\n{}",
        b"POST /validate/pod-privileged HTTP/1.1\r\nHost: t\r\n"
        b"Content-Length: 7\r\nTransfer-Encoding: chunked\r\n"
        b"Connection: close\r\n\r\n2\r\n{}\r\n0\r\n\r\n",
    ]
    for wire in vectors:
        for handle in (py, nat):
            out = send_raw(handle.server.api_port, wire)
            assert b" 400 " in out.split(b"\r\n", 1)[0], (wire[:60], out[:120])


def test_shed_429_carries_retry_after_natively():
    """ShedError at admission must answer HTTP 429 + Retry-After from the
    native completion path (header parity with api/handlers)."""
    import concurrent.futures

    from policy_server_tpu.telemetry import metrics as metrics_mod

    metrics_mod.reset_metrics_for_tests()
    from policy_server_tpu.evaluation.environment import bucket_size

    handle = ServerHandle(
        make_config(
            frontend="native",
            request_timeout_ms=100.0,
            max_batch_size=2,
            batch_timeout_ms=5.0,
            policy_timeout_seconds=30.0,
        )
    )
    try:
        # teach the estimator a pathologically slow device (the unit-test
        # pattern from test_resilience): any nonzero queue depth now
        # exceeds the 100 ms budget, so concurrent arrivals shed
        handle.server.batcher._dev_rtt[bucket_size(2)] = 50.0
        url = handle.url("/validate/pod-privileged")
        body = pod_review_body(False)

        def one():
            try:
                r = requests.post(
                    url, json=body,
                    headers={"Connection": "close"}, timeout=60,
                )
                return r.status_code, r.headers.get("Retry-After")
            except requests.RequestException:
                return None, None

        with concurrent.futures.ThreadPoolExecutor(64) as pool:
            results = list(pool.map(lambda _i: one(), range(128)))
        sheds = [ra for code, ra in results if code == 429]
        assert sheds, f"no shed 429s at this load: {results[:10]}"
        assert all(ra is not None and int(ra) >= 1 for ra in sheds)
    finally:
        handle.stop()


# -- graceful degradation ----------------------------------------------------


def test_fallback_when_extension_unavailable(monkeypatch):
    """--frontend native with a missing/broken extension must boot the
    Python frontend with ONE loud warning and serve normally (the
    fetch/verify soft-dep pattern from round 7)."""
    from policy_server_tpu.runtime import native_frontend as mod
    from policy_server_tpu.telemetry import metrics as metrics_mod

    metrics_mod.reset_metrics_for_tests()
    monkeypatch.setattr(mod, "_lib", None)
    monkeypatch.setattr(mod, "_lib_failed", True)
    handle = ServerHandle(make_config(frontend="native"))
    try:
        assert handle.server._native_frontend is None
        assert handle.server.state.native_frontend is None
        r = requests.post(
            handle.url("/validate/pod-privileged"),
            json=pod_review_body(True),
            timeout=60,
        )
        assert r.status_code == 200
        assert r.json()["response"]["allowed"] is False
    finally:
        handle.stop()


def test_prefork_workers_own_native_loops():
    """--http-workers with --frontend native: each prefork worker becomes
    a thin owner of its own native event loop (SO_REUSEPORT), forwarding
    parsed frames over the evaluation bridge — verdicts must be
    indistinguishable across whichever process accepts the socket."""
    from policy_server_tpu.telemetry import metrics as metrics_mod

    metrics_mod.reset_metrics_for_tests()
    handle = ServerHandle(make_config(http_workers=3, frontend="native"))
    try:
        deadline = time.time() + 30
        while time.time() < deadline and len(handle.server._worker_procs) < 2:
            time.sleep(0.1)
        time.sleep(1.5)  # workers binding their native listeners
        assert handle.server._native_frontend is not None  # main process
        url = handle.url("/validate/pod-privileged")
        for i in range(12):  # fresh connections → kernel spreads processes
            r = requests.post(
                url, json=pod_review_body(i % 2 == 0),
                headers={"Connection": "close"}, timeout=60,
            )
            assert r.status_code == 200
            assert r.json()["response"]["allowed"] is (i % 2 != 0)
        # parse errors stay bit-exact through worker loops too
        r = requests.post(
            url, data=b"junk",
            headers={"Content-Type": "application/json",
                     "Connection": "close"},
            timeout=60,
        )
        assert r.status_code == 422
    finally:
        handle.stop()


def test_native_counters_reach_metrics_endpoint(pair):
    """The framing counters must be visible on /metrics with their
    declared (graftcheck-checked) family names."""
    _py, nat = pair
    requests.post(
        nat.url("/validate/pod-privileged"),
        json=pod_review_body(False),
        timeout=60,
    )
    text = requests.get(nat.readiness_url("/metrics"), timeout=30).text
    assert "policy_server_native_http_requests_total" in text
    assert "policy_server_native_framing_seconds_total" in text
    assert "policy_server_queue_wait_seconds_total" in text
    stats = nat.server._native_frontend.stats()
    assert stats["http_requests"] > 0
    assert stats["requests_parsed_native"] > 0


# -- round 13: drainer backpressure + connection-abuse hardening -------------


class _GatedSink:
    """Burst sink that blocks until released, then answers 200s — the
    deterministic way to wedge the drainer so the SPSC ring fills."""

    def __init__(self):
        import threading

        self.gate = threading.Event()

    def handle_burst(self, frontend, burst):
        self.gate.wait(timeout=30)
        for rec in burst:
            frontend.complete(rec[0], 200, b'{"ok": true}')


def _mini_frontend(sink, **kw):
    sock = nf.make_listen_socket("127.0.0.1", 0)
    port = sock.getsockname()[1]
    front = nf.NativeFrontend(sock, sink, **kw).start()
    return front, port


def test_ring_full_answers_inband_503_not_stall():
    """With the drainer wedged, a flood past the submission ring's
    capacity must answer in-band 503s (counted) from the epoll loop —
    never stall it — and the wedge's release must complete every
    admitted request."""
    sink = _GatedSink()
    front, port = _mini_frontend(sink, ring_bits=8)  # 256-slot ring
    try:
        s = socket.create_connection(("127.0.0.1", port))
        one = post_bytes("/validate/p", b"{}", close=False)
        s.sendall(one)  # latches the drainer into the blocked sink
        time.sleep(0.3)
        flood = b"".join(
            post_bytes("/validate/p", b"{}", close=False)
            for _ in range(600)
        )
        s.sendall(flood)
        deadline = time.time() + 10
        while (
            time.time() < deadline
            and front.stats()["ring_full_rejections"] == 0
        ):
            time.sleep(0.05)
        assert front.stats()["ring_full_rejections"] > 0, (
            "flood never overran the 256-slot ring"
        )
        sink.gate.set()
        # every request answers: 200 (drained) or 503 (ring-full)
        s.settimeout(20)
        stream = b""
        try:
            while stream.count(b"HTTP/1.1 ") < 601:
                chunk = s.recv(1 << 20)
                if not chunk:
                    break
                stream += chunk
        except socket.timeout:
            pass
        resps = parse_responses(stream)
        assert len(resps) == 601, len(resps)
        codes = [st.split(" ")[1] for st, _h, _b in resps]
        # compare against the counter AFTER every response is in: a
        # snapshot taken while the flood is still hitting the full ring
        # undercounts the rejections that land between snapshot and
        # gate-release (observed 212 counted vs 344 final in CI)
        rejected = front.stats()["ring_full_rejections"]
        assert codes.count("503") == rejected
        assert codes.count("200") == 601 - rejected
        s.close()
    finally:
        sink.gate.set()
        front.shutdown(timeout=5)


class _EchoSink:
    def handle_burst(self, frontend, burst):
        for rec in burst:
            frontend.complete(rec[0], 200, b'{"ok": true}')


def test_read_timeout_reaps_slowloris_and_idle_conns():
    """A request dripping forever (slowloris) must be reaped by the
    read timeout; a silent keep-alive conn by the idle timeout — both
    counted, with served conns untouched in between."""
    front, port = _mini_frontend(
        _EchoSink(), read_timeout_ms=1000, idle_timeout_ms=2500
    )
    try:
        # slowloris: header never completes
        slow = socket.create_connection(("127.0.0.1", port))
        slow.sendall(b"POST /validate/p HTTP/1.1\r\n")
        # idle: one served request, then silence
        idle = socket.create_connection(("127.0.0.1", port))
        idle.sendall(post_bytes("/validate/p", b"{}", close=False))
        idle.settimeout(10)
        assert b" 200 " in idle.recv(65536)

        def reaped(sock_, drip):
            deadline = time.time() + 8
            while time.time() < deadline:
                try:
                    if drip:
                        sock_.sendall(b"X")
                    sock_.settimeout(0.3)
                    try:
                        if sock_.recv(4096) == b"":
                            return True
                    except socket.timeout:
                        pass
                except OSError:
                    return True
                time.sleep(0.2)
            return False

        assert reaped(slow, drip=True), "slowloris conn never reaped"
        assert reaped(idle, drip=False), "idle conn never reaped"
        assert front.stats()["idle_timeout_closes"] >= 2
        # the port still serves
        ok = socket.create_connection(("127.0.0.1", port))
        ok.sendall(post_bytes("/validate/p", b"{}"))
        ok.settimeout(10)
        assert b" 200 " in ok.recv(65536)
        ok.close()
    finally:
        front.shutdown(timeout=5)


def test_continuous_pipelining_outlives_read_timeout():
    """The read-timeout clock is per REQUEST arrival, not per buffer
    drain: a healthy client pipelining back-to-back requests for longer
    than the read timeout (its buffer often holding a partial tail)
    must never be reaped mid-stream — each completed request resets the
    clock (regression: the clock used to clear only when the input
    buffer drained to a clean boundary)."""
    front, port = _mini_frontend(
        _EchoSink(), read_timeout_ms=700, idle_timeout_ms=60_000
    )
    try:
        s = socket.create_connection(("127.0.0.1", port))
        s.settimeout(10)
        one = post_bytes("/validate/p", b"{}", close=False)
        head, tail = one[: len(one) // 2], one[len(one) // 2:]
        # every burst ENDS with a partial request, so the server's input
        # buffer never drains to a clean boundary for the whole run —
        # the old clock (cleared only on a drained buffer) starts once
        # and reaps this healthy conn at 700 ms
        s.sendall(one + head)
        sent = 1
        stream = b""
        deadline = time.time() + 2.5  # ~3.5x the read timeout
        while time.time() < deadline:
            while stream.count(b"HTTP/1.1 ") < sent:
                chunk = s.recv(1 << 16)
                assert chunk, (
                    "server closed a continuously pipelining conn "
                    f"after {stream.count(b'HTTP/1.1 ')} of {sent} "
                    "responses"
                )
                stream += chunk
            s.sendall(tail + one + head)  # completes 2, leaves 1 partial
            sent += 2
            time.sleep(0.05)
        s.sendall(tail)  # finish the last partial
        while stream.count(b"HTTP/1.1 ") < sent:
            chunk = s.recv(1 << 16)
            assert chunk, "server closed the conn on the final drain"
            stream += chunk
        resps = parse_responses(stream)
        assert len(resps) == sent and sent >= 20
        assert all(" 200 " in st for st, _h, _b in resps)
        assert front.stats()["idle_timeout_closes"] == 0
        s.close()
    finally:
        front.shutdown(timeout=5)


def test_connection_cap_rejects_inband_503():
    """Accepts over --native-max-connections answer an in-band 503 +
    Retry-After and close (counted) instead of silently dropping."""
    front, port = _mini_frontend(_EchoSink(), max_connections=2)
    try:
        held = [
            socket.create_connection(("127.0.0.1", port))
            for _ in range(2)
        ]
        time.sleep(0.3)  # both registered by the event loop
        over = socket.create_connection(("127.0.0.1", port))
        over.settimeout(10)
        data = b""
        while True:
            try:
                chunk = over.recv(65536)
            except socket.timeout:
                break
            if not chunk:
                break
            data += chunk
        assert b" 503 " in data.split(b"\r\n", 1)[0], data[:120]
        assert b"connection limit reached" in data
        assert b"retry-after" in data.lower()
        assert front.stats()["conn_cap_rejections"] == 1
        over.close()
        # capacity frees as held conns close
        held[0].close()
        time.sleep(1.2)
        again = socket.create_connection(("127.0.0.1", port))
        again.sendall(post_bytes("/validate/p", b"{}"))
        again.settimeout(10)
        assert b" 200 " in again.recv(65536)
        again.close()
        held[1].close()
    finally:
        front.shutdown(timeout=5)


def test_record_timestamps_and_traceparent_cross_the_ring():
    """Round 18: every record carries CLOCK_MONOTONIC stamps (received,
    canonicalized+pushed) plus the verbatim traceparent header; the
    drainer records the native accept/parse/ring-cross phase aggregates
    on the flight recorder."""
    import threading as _threading
    import time as _time

    from policy_server_tpu.telemetry import flightrec

    class _CaptureSink:
        def __init__(self):
            self.bursts = []
            self.got = _threading.Event()

        def handle_burst(self, frontend, burst):
            self.bursts.append(list(burst))
            for rec in burst:
                frontend.complete(rec[0], 200, b'{"ok": true}')
            self.got.set()

    rec = flightrec.install(flightrec.FlightRecorder(capacity=1024))
    sink = _CaptureSink()
    front, port = _mini_frontend(sink)
    try:
        tp = "00-4bf92f3577b34da6a3ce929d0e0e4736-00f067aa0ba902b7-01"
        body = review()
        t_before = _time.perf_counter_ns()
        req = (
            b"POST /validate/priv HTTP/1.1\r\nHost: x\r\n"
            + f"traceparent: {tp}\r\n".encode()
            + f"Content-Length: {len(body)}\r\n\r\n".encode()
            + body
        )
        send_raw(port, req)
        assert sink.got.wait(timeout=15)
        t_after = _time.perf_counter_ns()
    finally:
        front.shutdown()
        flightrec.install(None)
    (burst,) = sink.bursts
    (record,) = burst
    # tuple: (req_id, kind, policy, uid, ns, op, gvk, payload, tp,
    #         t_first, t_parse, t_push)
    assert record[8] == tp
    _tf, t_parse, t_push = record[9], record[10], record[11]
    assert t_before < t_parse <= t_push < t_after
    phases = {e["phase"] for e in rec.snapshot()}
    assert {
        flightrec.PH_NATIVE_ACCEPT,
        flightrec.PH_NATIVE_PARSE,
        flightrec.PH_RING_CROSS,
    } <= phases
    for e in rec.snapshot():
        assert e["end_ns"] >= e["start_ns"]


def test_obs_text_traceparent_never_kills_the_drainer():
    """Post-review regression: HTTP/1.1 field values legally carry
    obs-text bytes 0x80-0xFF; a traceparent full of them must be
    dropped at the C++ header gate (and the Python decode is
    errors='replace' as defense in depth) — never a strict-decode
    raise that kills the drain thread and strands the burst."""
    import threading as _threading

    class _CaptureSink:
        def __init__(self):
            self.records = []
            self.got = _threading.Event()

        def handle_burst(self, frontend, burst):
            self.records.extend(burst)
            for rec in burst:
                frontend.complete(rec[0], 200, b'{"ok": true}')
            if len(self.records) >= 2:
                self.got.set()

    sink = _CaptureSink()
    front, port = _mini_frontend(sink)
    try:
        body = review()
        bad = (
            b"POST /validate/priv HTTP/1.1\r\nHost: x\r\n"
            b"traceparent: \xff\xfe\x80garbage\r\n"
            + f"Content-Length: {len(body)}\r\n\r\n".encode()
            + body
        )
        resp = send_raw(port, bad)
        assert b"200" in resp.split(b"\r\n", 1)[0]
        # the drainer survived: a SECOND request still drains and answers
        ok = (
            b"POST /validate/priv HTTP/1.1\r\nHost: x\r\n"
            + f"Content-Length: {len(body)}\r\n\r\n".encode()
            + body
        )
        resp = send_raw(port, ok)
        assert b"200" in resp.split(b"\r\n", 1)[0]
        assert sink.got.wait(timeout=15)
    finally:
        front.shutdown()
    # the obs-text header never crossed the ring
    assert sink.records[0][8] == ""
