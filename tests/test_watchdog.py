"""Dispatch-watchdog tests: device execution (compile stall, transport
hang) is bounded by the per-request deadline, mirroring the reference's
mid-execution epoch interrupt (src/lib.rs:176-190, "execution deadline
exceeded" in tests/integration_test.rs:417). No request future may outlive
``policy_timeout`` unresolved, and a wedged device call must not take the
dispatch loop down with it."""

from __future__ import annotations

import threading
import time

import pytest

from policy_server_tpu.api.service import RequestOrigin
from policy_server_tpu.evaluation.environment import EvaluationEnvironmentBuilder
from policy_server_tpu.models import AdmissionReviewRequest, ValidateRequest
from policy_server_tpu.models.policy import parse_policy_entry
from policy_server_tpu.runtime.batcher import DEADLINE_MESSAGE, MicroBatcher
from policy_server_tpu.telemetry import metrics as metrics_mod

from conftest import build_admission_review_dict


@pytest.fixture(autouse=True)
def fresh_metrics():
    metrics_mod.reset_metrics_for_tests()
    yield
    metrics_mod.reset_metrics_for_tests()


def review() -> ValidateRequest:
    return ValidateRequest.from_admission(
        AdmissionReviewRequest.from_dict(build_admission_review_dict()).request
    )


def wedge_device_half(env, gate_fn):
    """Wrap whichever callable the batcher's device path will block on —
    validate_batch_finish on the split (double-buffered) native pipeline,
    validate_batch otherwise — so a simulated hang/stall lands exactly
    where a real device wait would. Returns an undo callable."""
    if env.native_encoding:
        real = env.validate_batch_finish

        def wrapped(handle):
            gate_fn()
            return real(handle)

        env.validate_batch_finish = wrapped
        return lambda: setattr(env, "validate_batch_finish", real)
    real = env.validate_batch

    def wrapped(items, run_hooks=True):
        gate_fn()
        return real(items, run_hooks=run_hooks)

    env.validate_batch = wrapped
    return lambda: setattr(env, "validate_batch", real)


@pytest.fixture()
def env():
    policies = {
        "ns": parse_policy_entry(
            "ns",
            {
                "module": "builtin://namespace-validate",
                "settings": {"denied_namespaces": ["blocked"]},
            },
        ),
    }
    return EvaluationEnvironmentBuilder(backend="jax").build(policies)


def test_hung_device_call_rejects_in_band_and_loop_survives(env):
    """A transport hang (device results never arriving) must resolve every
    waiting future with the deadline rejection within ~policy_timeout, and
    the NEXT batch must still be served (the hang wedges one device-pool
    worker, not the dispatch loop)."""
    release = threading.Event()
    hang_once = {"armed": True}

    def gate():
        if hang_once["armed"]:
            hang_once["armed"] = False
            release.wait(timeout=30)  # simulated hung device_get

    undo = wedge_device_half(env, gate)
    batcher = MicroBatcher(
        env, max_batch_size=4, batch_timeout_ms=1.0, policy_timeout=0.5,
        host_fastpath_threshold=0,  # these tests exercise the DEVICE path
        latency_budget_ms=0,  # keep the budget router from bypassing it
    ).start()
    try:
        t0 = time.perf_counter()
        fut = batcher.submit("ns", review(), RequestOrigin.VALIDATE)
        resp = fut.result(timeout=5)  # watchdog, not the hang, bounds this
        elapsed = time.perf_counter() - t0
        assert resp.allowed is False
        assert resp.status.code == 500
        assert DEADLINE_MESSAGE in resp.status.message
        assert elapsed < 3.0
        assert batcher.deadline_abandoned_batches == 1
        # loop is alive: a second submission dispatches on a fresh worker
        fut2 = batcher.submit("ns", review(), RequestOrigin.VALIDATE)
        assert fut2.result(timeout=10).allowed is True
    finally:
        release.set()
        batcher.shutdown()
        undo()


def test_cold_bucket_compile_stall_bounded_then_fast(env):
    """A compile stall on a cold (schema × batch) bucket: the first request
    is deadline-rejected in-band while compilation finishes in the
    background; once warm, the same bucket serves within the deadline."""
    stall = {"first": True}

    def gate():
        if stall["first"]:
            stall["first"] = False
            time.sleep(1.2)  # simulated cold-bucket XLA compile

    # a compile stall surfaces in the HOST half (the jit dispatch runs in
    # validate_batch_begin) — wedge that half on the split pipeline so
    # this test proves the encode-stage watchdog too
    if env.native_encoding:
        real_begin = env.validate_batch_begin

        def stalling_begin(items, run_hooks=True):
            gate()
            return real_begin(items, run_hooks=run_hooks)

        env.validate_batch_begin = stalling_begin
        undo = lambda: setattr(env, "validate_batch_begin", real_begin)  # noqa: E731
    else:
        undo = wedge_device_half(env, gate)
    batcher = MicroBatcher(
        env, max_batch_size=4, batch_timeout_ms=1.0, policy_timeout=0.4,
        host_fastpath_threshold=0,
        latency_budget_ms=0,
    ).start()
    try:
        cold = batcher.submit("ns", review(), RequestOrigin.VALIDATE)
        resp = cold.result(timeout=5)
        assert resp.status.code == 500
        assert DEADLINE_MESSAGE in resp.status.message
        time.sleep(1.3)  # let the background compile finish
        warm = batcher.submit("ns", review(), RequestOrigin.VALIDATE)
        assert warm.result(timeout=10).allowed is True
    finally:
        batcher.shutdown()
        undo()


def test_timeout_disabled_keeps_unbounded_execution(env):
    """``--policy-timeout 0`` disables the deadline (src/cli.rs:164-169):
    a slow device call then completes normally instead of being cut."""
    real = env.validate_batch

    def slow_validate_batch(items, run_hooks=True):
        time.sleep(0.3)
        return real(items, run_hooks=run_hooks)

    env.validate_batch = slow_validate_batch
    batcher = MicroBatcher(
        env, max_batch_size=4, batch_timeout_ms=1.0, policy_timeout=None,
        host_fastpath_threshold=0,
        latency_budget_ms=0,
    ).start()
    try:
        fut = batcher.submit("ns", review(), RequestOrigin.VALIDATE)
        assert fut.result(timeout=10).allowed is True
        assert batcher.deadline_abandoned_batches == 0
    finally:
        batcher.shutdown()
        env.validate_batch = real


def test_partial_expiry_late_items_still_served(env):
    """Items with later deadlines stay live after earlier items expire:
    the watchdog rejects progressively, not batch-at-once."""
    release = threading.Event()
    entered = threading.Event()
    calls = {"n": 0}

    def gate():
        calls["n"] += 1
        if calls["n"] == 1:
            entered.set()
            release.wait(timeout=30)

    undo = wedge_device_half(env, gate)
    # max_batch_size=1 → each submission is its own batch; the first wedges
    # one device worker, the second runs concurrently on another.
    batcher = MicroBatcher(
        env, max_batch_size=1, batch_timeout_ms=0.1, policy_timeout=0.6,
        host_fastpath_threshold=0,
        latency_budget_ms=0,
    ).start()
    try:
        doomed = batcher.submit("ns", review(), RequestOrigin.VALIDATE)
        # wait until doomed's device half is provably the wedged one —
        # submitting both back-to-back would race which batch's device
        # half reaches the gate first (wider window under the split
        # pipeline, whose host half does real encode work)
        assert entered.wait(timeout=5), "doomed batch never reached device"
        ok = batcher.submit("ns", review(), RequestOrigin.VALIDATE)
        assert ok.result(timeout=10).allowed is True
        resp = doomed.result(timeout=5)
        assert resp.status.code == 500
        assert DEADLINE_MESSAGE in resp.status.message
    finally:
        release.set()
        batcher.shutdown()
        undo()
