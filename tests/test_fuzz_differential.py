"""Property-based differential fuzzing (hypothesis): arbitrary pod-shaped
and adversarial JSON AdmissionReviews must produce BIT-EXACT responses
from the device (jax) backend and the host IR oracle, and verdict-equal
results from the wasm oracle where one exists.

This is the generative extension of tests/test_differential.py's fixed
corpora — the tensorization codec (SURVEY.md §7.4 hard-part #1) is the
hardest correctness surface, and random structure is what breaks codecs."""

from __future__ import annotations

import pytest

pytest.importorskip("hypothesis")
from hypothesis import HealthCheck, given, settings
from hypothesis import strategies as st

from policy_server_tpu.evaluation.environment import EvaluationEnvironmentBuilder
from policy_server_tpu.models import AdmissionReviewRequest, ValidateRequest
from policy_server_tpu.models.policy import parse_policy_entry

from conftest import build_admission_review_dict

POLICIES = {
    "priv": {"module": "builtin://pod-privileged"},
    "ns": {
        "module": "builtin://namespace-validate",
        "settings": {"denied_namespaces": ["blocked", "kube-system"]},
    },
    "latest": {"module": "builtin://disallow-latest-tag"},
    "hostns": {"module": "builtin://host-namespaces"},
    "caps": {
        "module": "builtin://psp-capabilities",
        "settings": {
            "allowed_capabilities": ["CHOWN"],
            "required_drop_capabilities": ["NET_ADMIN"],
        },
    },
    "grp": {
        "expression": "unpriv() && tagged()",
        "message": "group denied",
        "policies": {
            "unpriv": {"module": "builtin://pod-privileged"},
            "tagged": {"module": "builtin://disallow-latest-tag"},
        },
    },
}


@pytest.fixture(scope="module")
def envs():
    entries = {k: parse_policy_entry(k, v) for k, v in POLICIES.items()}
    return (
        EvaluationEnvironmentBuilder(backend="jax").build(entries),
        EvaluationEnvironmentBuilder(backend="oracle").build(entries),
    )


# -- strategies --------------------------------------------------------------

_names = st.text(
    alphabet="abcdefghijklmnopqrstuvwxyz0123456789-.", min_size=0, max_size=12
)
_images = st.one_of(
    st.just(""),
    _names,
    st.builds(
        lambda reg, repo, tag: f"{reg}/{repo}{tag}",
        st.sampled_from(["docker.io", "ghcr.io/x", "localhost:5000", "r"]),
        _names,
        st.sampled_from(["", ":latest", ":1.2", "@sha256:abc", ":"]),
    ),
)
_scalar = st.one_of(
    st.none(),
    st.booleans(),
    st.integers(min_value=-(2**31), max_value=2**31 - 1),
    st.floats(allow_nan=False, allow_infinity=False, width=32),
    _names,
)


def _security_context():
    return st.fixed_dictionaries(
        {},
        optional={
            "privileged": st.one_of(st.booleans(), st.none(), _names),
            "runAsNonRoot": st.booleans(),
            "readOnlyRootFilesystem": st.booleans(),
            "capabilities": st.fixed_dictionaries(
                {},
                optional={
                    "add": st.lists(
                        st.sampled_from(
                            ["CHOWN", "NET_ADMIN", "SYS_ADMIN", "KILL"]
                        ),
                        max_size=4,
                    ),
                    "drop": st.lists(
                        st.sampled_from(["NET_ADMIN", "ALL"]), max_size=3
                    ),
                },
            ),
        },
    )


def _container():
    return st.fixed_dictionaries(
        {},
        optional={
            "name": _names,
            "image": _images,
            "securityContext": st.one_of(_security_context(), st.none()),
        },
    )


def _pod_object():
    return st.one_of(
        st.none(),
        _scalar,  # adversarial: object is not even a mapping
        st.fixed_dictionaries(
            {},
            optional={
                "metadata": st.fixed_dictionaries(
                    {},
                    optional={
                        "name": _names,
                        "labels": st.dictionaries(_names, _scalar, max_size=3),
                    },
                ),
                "spec": st.one_of(
                    st.none(),
                    st.fixed_dictionaries(
                        {},
                        optional={
                            "containers": st.one_of(
                                st.none(),
                                st.lists(_container(), max_size=5),
                            ),
                            "initContainers": st.lists(_container(), max_size=2),
                            "hostNetwork": st.one_of(st.booleans(), _names),
                            "hostPID": st.booleans(),
                            "hostIPC": st.booleans(),
                        },
                    ),
                ),
            },
        ),
    )


def _review(namespace: str, obj) -> ValidateRequest:
    doc = build_admission_review_dict()
    doc["request"]["namespace"] = namespace
    doc["request"]["object"] = obj
    return ValidateRequest.from_admission(
        AdmissionReviewRequest.from_dict(doc).request
    )


@settings(
    max_examples=60,
    deadline=None,
    suppress_health_check=[HealthCheck.function_scoped_fixture],
)
@given(
    namespace=st.sampled_from(["default", "blocked", "kube-system", "", "x"]),
    obj=_pod_object(),
    policy=st.sampled_from(sorted(POLICIES)),
)
def test_device_matches_oracle_on_random_reviews(envs, namespace, obj, policy):
    jax_env, oracle_env = envs
    a = jax_env.validate(policy, _review(namespace, obj))
    b = oracle_env.validate(policy, _review(namespace, obj))
    assert a.to_dict() == b.to_dict(), (policy, namespace, obj)


@settings(
    max_examples=30,
    deadline=None,
    suppress_health_check=[HealthCheck.function_scoped_fixture],
)
@given(namespace=st.sampled_from(["default", "blocked"]), obj=_pod_object())
def test_device_matches_wasm_oracle_on_random_reviews(envs, namespace, obj):
    """Three-way: the WAT wasm policies agree with the device on verdicts
    for randomly structured pods."""
    from policy_server_tpu.policies.wasm_oracle import oracle_policy

    jax_env, _ = envs
    req = _review(namespace, obj)
    raw = req.payload()
    for name, pid in (
        ("pod-privileged", "priv"),
        ("namespace-validate", "ns"),
        ("disallow-latest-tag", "latest"),
        ("host-namespaces", "hostns"),
    ):
        dev = jax_env.validate(pid, _review(namespace, obj))
        wasm = oracle_policy(name).validate(
            raw, POLICIES[pid].get("settings", {})
        )
        assert bool(wasm.get("accepted")) == bool(dev.allowed), (
            name,
            namespace,
            obj,
        )
