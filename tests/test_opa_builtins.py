"""OPA builtins host registry tests (round-4 VERDICT item 2).

Covers the registry implementations directly, the full wasm ABI dispatch
(a WAT-authored OPA module declaring builtins and calling them through
``opa_builtin{1,2}``, tests/opa_builtin_fixture.py), the unknown-builtin
failure surface, and the serving path end-to-end (the module loaded as a
policy into the evaluation environment). Reference parity:
burrego's builtins set and banner (/root/reference/src/cli.rs:7-21)."""

from __future__ import annotations

import pytest

from policy_server_tpu.wasm import builtins as bi
from policy_server_tpu.wasm.opa import OpaPolicy, gatekeeper_validate

from opa_builtin_fixture import builtin_oracle_wasm


# ---------------------------------------------------------------------------
# registry unit tests
# ---------------------------------------------------------------------------


@pytest.mark.parametrize(
    "fmt,args,expected",
    [
        ("hello %s", ["world"], "hello world"),
        ("%d pods over %d", [3, 2], "3 pods over 2"),
        ("%v", [{"a": 1}], '{"a": 1}'),
        ("%v", [True], "true"),
        ("%05d", [42], "00042"),
        ("%.2f", [3.14159], "3.14"),
        ("%x", [255], "ff"),
        ("%q", ["x"], '"x"'),
        ("100%%", [], "100%"),
        ("%s %s", ["only"], "only %!s(MISSING)"),
    ],
)
def test_sprintf(fmt, args, expected):
    assert bi.REGISTRY["sprintf"](fmt, args) == expected


def test_string_builtins():
    r = bi.REGISTRY
    assert r["concat"]("/", ["a", "b", "c"]) == "a/b/c"
    assert r["contains"]("registry.io/img", "/") is True
    assert r["startswith"]("docker.io/nginx", "docker.io/") is True
    assert r["endswith"]("img:latest", ":latest") is True
    assert r["lower"]("ABC") == "abc"
    assert r["upper"]("abc") == "ABC"
    assert r["replace"]("a.b.c", ".", "-") == "a-b-c"
    assert r["split"]("a,b,c", ",") == ["a", "b", "c"]
    assert r["substring"]("kubernetes", 4, 3) == "rne"
    assert r["substring"]("kubernetes", 4, -1) == "rnetes"
    assert r["trim"]("xxaxx", "x") == "a"
    assert r["trim_space"]("  a\t") == "a"
    assert r["trim_prefix"]("docker.io/nginx", "docker.io/") == "nginx"
    assert r["trim_suffix"]("img:latest", ":latest") == "img"
    assert r["indexof"]("abcdef", "cd") == 2
    assert r["format_int"](255, 16) == "ff"
    assert r["format_int"](-7, 2) == "-111"


def test_regex_builtins():
    r = bi.REGISTRY
    assert r["regex.match"]("^docker\\.io/", "docker.io/nginx") is True
    assert r["regex.match"]("^ghcr\\.io/", "docker.io/nginx") is False
    assert r["re_match"]("ngin.", "docker.io/nginx") is True
    assert r["regex.is_valid"]("a(b") is False
    assert r["regex.split"](",\\s*", "a, b,c") == ["a", "b", "c"]
    assert r["regex.find_n"]("[a-z]+", "ab1cd2ef", 2) == ["ab", "cd"]
    assert r["regex.replace"]("a-b-c", "-", "+") == "a+b+c"
    # Go replacement syntax: $1/${name} are groups, $$ literal, lone $ literal
    assert r["regex.replace"]("ab", "(a)(b)", "${2}${1}") == "ba"
    assert r["regex.replace"]("ab", "(a)(b)", "$2$1") == "ba"
    assert r["regex.replace"]("price", "price", "cost $5") == "cost "  # Go: missing group -> empty
    assert r["regex.replace"]("x", "x", "$$1") == "$1"
    # full-match text even with capture groups
    assert r["regex.find_n"]("a(b)", "ab ab", -1) == ["ab", "ab"]
    with pytest.raises(bi.BuiltinError):
        r["regex.match"]("(bad", "x")


def test_glob_builtins():
    r = bi.REGISTRY
    # delimiter-aware *: does not cross separators
    assert r["glob.match"]("registry.io/*", ["/"], "registry.io/img") is True
    assert r["glob.match"]("registry.io/*", ["/"], "registry.io/a/b") is False
    assert r["glob.match"]("registry.io/**", ["/"], "registry.io/a/b") is True
    assert r["glob.match"]("*.example.com", None, "api.example.com") is True
    assert r["glob.match"]("*.example.com", None, "a.b.example.com") is False
    assert r["glob.match"]("img-?", ["/"], "img-1") is True
    assert r["glob.match"]("{a,b}.io", ["."], "b.io") is True
    assert r["glob.quote_meta"]("a*b") == "a\\*b"


def test_set_builtins():
    r = bi.REGISTRY
    assert r["intersection"]([[1, 2, 3], [2, 3, 4], [3, 2]]) == [2, 3]
    assert r["union"]([[1, 2], [2, 3]]) == [1, 2, 3]
    assert r["intersection"]([]) == []


def test_encoding_builtins():
    r = bi.REGISTRY
    assert r["json.marshal"]({"a": [1, True]}) == '{"a":[1,true]}'
    assert r["json.unmarshal"]('{"a":1}') == {"a": 1}
    assert r["json.is_valid"]("{") is False
    assert r["base64.encode"]("hi") == "aGk="
    assert r["base64.decode"]("aGk=") == "hi"
    assert r["base64.is_valid"]("aGk=") is True
    assert r["base64.is_valid"]("a?") is False
    assert r["base64url.encode_no_pad"]("hi") == "aGk"
    assert r["base64url.decode"]("aGk") == "hi"
    assert r["urlquery.encode"]("a b&c") == "a+b%26c"
    assert r["urlquery.decode"]("a+b%26c") == "a b&c"


def test_semver_builtins():
    r = bi.REGISTRY
    assert r["semver.compare"]("1.2.3", "1.2.3") == 0
    assert r["semver.compare"]("1.2.3", "1.10.0") == -1
    assert r["semver.compare"]("2.0.0", "2.0.0-rc.1") == 1
    assert r["semver.compare"]("1.0.0-alpha", "1.0.0-alpha.1") == -1
    assert r["semver.is_valid"]("1.2.3-rc.1+build5") is True
    assert r["semver.is_valid"]("1.2") is False
    with pytest.raises(bi.BuiltinError):
        r["semver.compare"]("not-a-version", "1.0.0")


def test_units_builtins():
    r = bi.REGISTRY
    assert r["units.parse_bytes"]("128Mi") == 128 * 1024 * 1024
    assert r["units.parse_bytes"]("1GB") == 10**9
    assert r["units.parse_bytes"]("42") == 42
    assert r["units.parse"]("500m") == 0.5
    assert r["units.parse"]("2Ki") == 2048
    # SI suffixes are case-sensitive: M is mega, m is milli
    assert r["units.parse"]("1M") == 10**6
    assert r["units.parse"]("1G") == 10**9
    with pytest.raises(bi.BuiltinError):
        r["units.parse_bytes"]("12parsecs")


def test_long_version_banners_builtins():
    from policy_server_tpu.config.cli import long_version

    banner = long_version()
    assert "Open Policy Agent/Gatekeeper implemented builtins:" in banner
    assert "  - sprintf" in banner
    assert "  - regex.match" in banner
    assert "  - units.parse_bytes" in banner


# ---------------------------------------------------------------------------
# wasm ABI dispatch through the interpreter
# ---------------------------------------------------------------------------


PRIV_REQUEST = {
    "uid": "u1",
    "kind": {"group": "", "version": "v1", "kind": "Pod"},
    "operation": "CREATE",
    "object": {
        "spec": {
            "containers": [
                {"name": "c", "securityContext": {"privileged": True}}
            ]
        }
    },
}

OK_REQUEST = {
    "uid": "u2",
    "kind": {"group": "", "version": "v1", "kind": "Pod"},
    "operation": "CREATE",
    "object": {"spec": {"containers": [{"name": "c"}]}},
}


def test_builtin_dispatch_through_wasm_abi():
    """The fixture declares 4 builtins and calls them all on the reject
    path; the violation messages prove every value round-tripped through
    the guest's own serializer."""
    policy = OpaPolicy(builtin_oracle_wasm())
    assert policy.builtins() == {
        "json.marshal": 0, "regex.match": 1, "sprintf": 2,
        "units.parse_bytes": 3,
    }
    allowed, message = gatekeeper_validate(policy, PRIV_REQUEST)
    assert allowed is False
    # sprintf output and the units.parse_bytes number, joined by the
    # gatekeeper aggregator
    assert message == "privileged container denied (pod); 134217728"
    allowed, message = gatekeeper_validate(policy, OK_REQUEST)
    assert allowed is True
    assert message is None


def test_wrong_arity_builtin_maps_to_wasm_trap():
    """A module binding a name at the wrong arity (host TypeError) must
    surface as a WasmTrap → in-band rejection, not a crashed handler."""
    from policy_server_tpu.wasm.interp import WasmTrap

    # 'lower' is unary; the fixture calls id 1 through opa_builtin2
    wasm = builtin_oracle_wasm(
        {"json.marshal": 0, "lower": 1, "sprintf": 2, "units.parse_bytes": 3}
    )
    policy = OpaPolicy(wasm)
    with pytest.raises(WasmTrap, match="OPA builtin lower"):
        gatekeeper_validate(policy, PRIV_REQUEST)


def test_unknown_builtin_fails_loudly():
    """A module declaring a builtin this host does not implement must fail
    with a deterministic error naming it (burrego behavior), not crash."""
    wasm = builtin_oracle_wasm(
        {"json.marshal": 0, "regex.match": 1, "sprintf": 2,
         "crypto.x509.parse_certificates": 3}
    )
    policy = OpaPolicy(wasm)
    from policy_server_tpu.wasm.interp import WasmTrap

    with pytest.raises(WasmTrap, match="crypto.x509.parse_certificates"):
        gatekeeper_validate(policy, PRIV_REQUEST)


def test_builtins_through_evaluation_environment(tmp_path):
    """Serving-path end-to-end: the builtin-calling module loads from a
    .wasm artifact and serves through the environment (device batch path
    routes host-executed wasm rows), with in-band builtin verdicts."""
    from policy_server_tpu.evaluation.environment import (
        EvaluationEnvironmentBuilder,
    )
    from policy_server_tpu.fetch.artifact import load_artifact
    from policy_server_tpu.models import (
        AdmissionReviewRequest,
        ValidateRequest,
    )
    from policy_server_tpu.models.policy import parse_policy_entry

    import conftest

    wasm_path = tmp_path / "builtins-policy.wasm"
    wasm_path.write_bytes(builtin_oracle_wasm())
    module = load_artifact(wasm_path)
    assert module.abi == "opa-gatekeeper"
    env = EvaluationEnvironmentBuilder(
        backend="jax", module_resolver=lambda url: module
    ).build(
        {
            "builtin-policy": parse_policy_entry(
                "builtin-policy", {"module": "file:///builtins.wasm"}
            )
        }
    )

    def to_request(request_dict):
        doc = conftest.build_admission_review_dict()
        doc["request"] = {**doc["request"], **request_dict}
        return ValidateRequest.from_admission(
            AdmissionReviewRequest.from_dict(doc).request
        )

    rejected = env.validate("builtin-policy", to_request(PRIV_REQUEST))
    assert rejected.allowed is False
    assert "privileged container denied (pod)" in rejected.status.message
    accepted = env.validate("builtin-policy", to_request(OK_REQUEST))
    assert accepted.allowed is True
    # the host fast-path routes host-executed rows identically
    (fast,) = env.validate_batch(
        [("builtin-policy", to_request(PRIV_REQUEST))], prefer_host=True
    )
    assert fast.to_dict() == rejected.to_dict()
